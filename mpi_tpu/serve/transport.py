"""Front-end-agnostic HTTP application core for the serve stack.

PR 7 splits ``serve/httpd.py`` in two: the request *semantics* — routing,
validation, session-manager verbs, error→status mapping, observability,
wire-format negotiation — live here in :class:`AppCore`, while the
*transports* own sockets and bytes: the threaded stdlib front
(``serve/httpd.py``, the default — byte-compatible with the PR-6
responses) and the selectors-based non-blocking front (``serve/aio.py``)
both feed :meth:`AppCore.dispatch` a :class:`Request` and write back the
:class:`Response` it returns.  One core, N fronts — the two can never
drift on a route or an error shape.

Wire-format negotiation (the binary protocol rides here so every front
gets it for free):

* ``GET /sessions/<id>/snapshot`` — ``Accept: application/x-gol-grid``
  answers one binary frame (``serve/wire.py``); anything else answers
  the PR-1 JSON shape, byte-identical.  Both come from the same
  ``SessionManager.snapshot_array`` fetch, so the formats cannot
  disagree about the grid.
* ``PUT /sessions/<id>/board`` — board write.  ``Content-Type:
  application/x-gol-grid`` sends a binary frame (its header's
  generation field, when flagged, rebases the session's generation);
  JSON sends ``{"grid": ['0101', ...], "generation": optional}``.
* ``GET /result/<ticket>`` — with binary ``Accept``: a *done* ticket
  answers a frame of the session's current grid; pending/error answer
  the usual JSON (status codes carry the semantics either way).
* ``GET /stream/<sid>?every=k`` — returns a :class:`StreamPlan`; only
  the aio front can park a socket and push frames, so the core answers
  a structured 501 on any other transport.

Request bodies are bounded (``--http-max-body``): a ``Content-Length``
over the bound answers a structured 413 *before any body byte is read*,
and the connection is closed (the unread body makes keep-alive framing
unrecoverable).
"""

from __future__ import annotations

import itertools
import json
import math
import os
import sys
import tempfile
import time
import traceback
from typing import Callable, List, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from mpi_tpu.admission.quota import AdmissionReject, retry_after_header
from mpi_tpu.cluster.proxy import (
    FORWARDED_HEADER, SESSION_ID_HEADER, PeerUnreachable, proxy_request,
)
from mpi_tpu.config import ConfigError
from mpi_tpu.obs.trace import reset_request_id, set_request_id
from mpi_tpu.obs.tracectx import (
    TRACEPARENT_HEADER, current_trace_context, format_traceparent, mint,
    parse_traceparent, reset_trace_context, set_trace_context, stitch_spans,
)
from mpi_tpu.serve import wire
from mpi_tpu.serve.recovery import StorageDegradedError
from mpi_tpu.serve.session import (
    DeadlineError, EngineStepError, EngineUnavailableError, SessionManager,
    TicketQueueFullError, format_grid_rows, parse_grid_rows,
)

DEFAULT_MAX_BODY = 64 << 20             # 64 MiB

# a scraper negotiates exemplar-capable output by naming this media type
# in Accept; everything else gets the byte-identical Prometheus text
OPENMETRICS_MEDIA_TYPE = "application/openmetrics-text"

# admission control (ISSUE 16): the request's tenant and an optional
# priority-class override.  Only read when admission is armed; unarmed
# servers never look at either, so their behavior is byte-identical.
TENANT_HEADER = "X-Gol-Tenant"
CLASS_HEADER = "X-Gol-Class"


class Request:
    """What a front hands the core: parsed request line + headers plus a
    lazy body reader (``read(n)``) — the core decides whether the body
    is ever read (the 413 path never reads it)."""

    __slots__ = ("method", "path", "headers", "read")

    def __init__(self, method: str, path: str, headers,
                 read: Callable[[int], bytes]):
        self.method = method
        self.path = path
        self.headers = headers          # any mapping with .get(name)
        self.read = read


class Response:
    """What the core hands back: status + body + content type, plus any
    extra headers and whether the connection must close after the write
    (the 413 path — an unread body poisons keep-alive framing)."""

    __slots__ = ("code", "body", "content_type", "headers", "close")

    def __init__(self, code: int, body: bytes, content_type: str,
                 headers: Optional[List[Tuple[str, str]]] = None,
                 close: bool = False):
        self.code = code
        self.body = body
        self.content_type = content_type
        self.headers = headers or []
        self.close = close


class StreamPlan:
    """A negotiated ``GET /stream/<sid>?every=k``: the aio front turns
    this into a chunked-transfer push stream of binary frames.  Fronts
    that cannot stream never see one — the core answers 501 for them.

    ``window`` (``(x0, y0, h, w)`` or None) restricts pushes to one
    viewport; ``delta`` switches the stream to dirty-tile delta frames
    with a keyframe on subscribe and every ``keyframe_every`` pushes."""

    __slots__ = ("sid", "every", "code", "window", "delta",
                 "keyframe_every")

    def __init__(self, sid: str, every: int, window=None,
                 delta: bool = False, keyframe_every: int = 64):
        self.sid = sid
        self.every = int(every)
        self.code = 200
        self.window = window
        self.delta = bool(delta)
        self.keyframe_every = int(keyframe_every)


def json_response(code: int, payload: dict, close: bool = False) -> Response:
    # the one JSON encoder both fronts share — byte-identical to the
    # PR-6 handler's json.dumps(payload).encode()
    return Response(code, json.dumps(payload).encode(),
                    "application/json", close=close)


class AppCore:
    """The transport-agnostic request handler.

    A front constructs one core at server build time and calls
    :meth:`dispatch` per request from whatever thread (or event loop
    callback) it likes — the core is stateless between requests apart
    from the shared request-id counter, and every manager verb it calls
    is already thread-safe.
    """

    def __init__(self, manager: Optional[SessionManager] = None,
                 verbose: bool = False,
                 profile_dir: Optional[str] = None,
                 max_body: int = DEFAULT_MAX_BODY):
        self.manager = manager if manager is not None else SessionManager()
        self.verbose = verbose
        self.profile_dir = profile_dir
        if max_body < 1:
            raise ValueError(f"max_body must be >= 1, got {max_body}")
        self.max_body = int(max_body)
        self.request_ids = itertools.count(1)
        self.obs = self.manager.obs
        # cluster membership (mpi_tpu/cluster), attached by serve_main
        # after the socket binds; None routes every request locally —
        # the pre-cluster behavior, byte-for-byte
        self.cluster = None

    # -- byte accounting (fronts call count_out for stream pushes too) -----

    def count_in(self, n: int, transport: str) -> None:
        if self.obs is not None and n:
            self.obs.http_bytes_in.inc(n, transport=transport)

    def count_out(self, n: int, transport: str) -> None:
        if self.obs is not None and n:
            self.obs.http_bytes_out.inc(n, transport=transport)

    # -- entry point -------------------------------------------------------

    def dispatch(self, req: Request, transport: str):
        """Handle one request; returns a :class:`Response` (or a
        :class:`StreamPlan` when ``transport == "aio"`` negotiated a
        stream).  Never raises — every failure maps to a structured
        JSON status, same discipline as the PR-3 handler."""
        rid = next(self.request_ids)
        obs = self.obs
        if obs is None:
            resp = self._guard(req, rid, None, transport)
        else:
            # one shared id per request: every span recorded while this
            # request is handled — here, in the watchdog worker, in the
            # batch leader — carries it (JSONL reconstructability).  The
            # trace context rides the same contextvar discipline: a
            # proxied hop continues the remote trace off its traceparent
            # header, anything else mints a fresh one at this edge.
            tctx = parse_traceparent(
                req.headers.get(TRACEPARENT_HEADER)) or mint()
            token = set_request_id(rid)
            ttoken = set_trace_context(tctx)
            t0 = time.perf_counter()
            try:
                with obs.span("http_request", method=req.method,
                              path=req.path) as sp:
                    resp = self._guard(req, rid, obs, transport)
                    sp.tag(code=resp.code)
                obs.http_requests.inc(method=req.method, code=resp.code)
                tel = obs.telemetry
                if tel is not None:
                    tel.http_digest.observe(time.perf_counter() - t0)
            finally:
                reset_trace_context(ttoken)
                reset_request_id(token)
            if not isinstance(resp, StreamPlan):
                # echo the served identity (the http_request span, so a
                # client following the /stream 307 re-propagates it and
                # the owner's spans stitch under this hop)
                resp.headers.append((TRACEPARENT_HEADER, format_traceparent(
                    sp.ctx if sp.ctx is not None else tctx)))
        if not isinstance(resp, StreamPlan):
            self.count_out(len(resp.body), transport)
        if self.verbose:
            print(f"[mpi_tpu] request {rid}: {req.method} {req.path} -> "
                  f"{resp.code}", file=sys.stderr)
        return resp

    # -- request plumbing --------------------------------------------------

    def _content_length(self, req: Request) -> int:
        raw = req.headers.get("Content-Length")
        if not raw:
            return 0
        try:
            n = int(raw)
        except (TypeError, ValueError):
            raise ConfigError(f"Content-Length must be an integer, "
                              f"got {raw!r}")
        if n < 0:
            raise ConfigError(f"Content-Length must be >= 0, got {n}")
        return n

    def _raw_body(self, req: Request, transport: str) -> bytes:
        n = self._content_length(req)
        if n == 0:
            return b""
        data = req.read(n)
        self.count_in(len(data), transport)
        return data

    def _body(self, req: Request, transport: str) -> dict:
        raw = self._raw_body(req, transport)
        if not raw:
            return {}
        try:
            data = json.loads(raw)
        except json.JSONDecodeError as e:
            raise ConfigError(f"request body is not valid JSON: {e}")
        if not isinstance(data, dict):
            raise ConfigError("request body must be a JSON object")
        return data

    def _timeout_override(self, req: Request, body: dict) -> Optional[float]:
        """The request's explicit deadline override, or None to use the
        server default: ``?timeout_s=`` wins over a ``timeout_s`` body
        key.  (It is a transport parameter, not part of the board spec —
        the create body's strict key check never sees it.)"""
        qs = parse_qs(urlsplit(req.path).query)
        raw = qs["timeout_s"][0] if "timeout_s" in qs else body.pop(
            "timeout_s", None)
        if raw is None:
            return None
        try:
            return float(raw)
        except (TypeError, ValueError):
            raise ConfigError(f"timeout_s must be a number, got {raw!r}")

    def _query_flag(self, req: Request, name: str) -> bool:
        """A boolean query parameter (``?async=1``, ``?wait=true``)."""
        qs = parse_qs(urlsplit(req.path).query)
        return (qs.get(name, ["0"])[0].lower() in ("1", "true", "yes"))

    def _wants_binary(self, req: Request) -> bool:
        return wire.GRID_MEDIA_TYPE in (req.headers.get("Accept") or "")

    def _viewport(self, req: Request) -> Optional[Tuple[int, int, int, int]]:
        """The request's viewport ``(x0, y0, h, w)`` from its ``x0``,
        ``y0``, ``h``, ``w`` query parameters, or None when none are
        present.  Partial windows are an error — a typo'd parameter must
        not silently serve the full board."""
        qs = parse_qs(urlsplit(req.path).query)
        names = ("x0", "y0", "h", "w")
        present = [n for n in names if n in qs]
        if not present:
            return None
        missing = [n for n in names if n not in qs]
        if missing:
            raise ConfigError(
                f"viewport needs all of x0,y0,h,w (missing: "
                f"{','.join(missing)})")
        vals = []
        for n in names:
            raw = qs[n][0]
            try:
                vals.append(int(raw))
            except (TypeError, ValueError):
                raise ConfigError(f"{n} must be an int, got {raw!r}")
        return tuple(vals)

    def _sends_binary(self, req: Request) -> bool:
        ct = (req.headers.get("Content-Type") or "").split(";")[0].strip()
        return ct == wire.GRID_MEDIA_TYPE

    def _route(self, req: Request):
        """(kind, session_id, verb) from the path."""
        parts = [p for p in req.path.split("?")[0].split("/") if p]
        if parts == ["healthz"]:
            return "healthz", None, None
        if parts == ["stats"]:
            return "stats", None, None
        if parts == ["metrics"]:
            return "metrics", None, None
        if parts == ["usage"]:
            return "usage", None, None
        if parts == ["slo"]:
            return "slo", None, None
        if parts == ["debug", "profile"]:
            return "profile", None, None
        if parts == ["debug", "timeseries"]:
            return "timeseries", None, None
        if parts == ["debug", "flights"]:
            return "flights", None, None
        if parts == ["debug", "anomalies"]:
            return "anomalies", None, None
        if len(parts) == 3 and parts[:2] == ["debug", "trace"]:
            return "trace", parts[2], None      # parts[2] is the trace id
        if parts and parts[0] == "cluster":
            # served only in cluster mode (self.cluster set); otherwise
            # falls through _handle to the usual structured 404
            if len(parts) == 1:
                return "cluster", None, None
            if len(parts) == 2:
                return "cluster", None, parts[1]
        if len(parts) == 2 and parts[0] == "result":
            return "result", parts[1], None     # parts[1] is the ticket id
        if len(parts) == 2 and parts[0] == "stream":
            return "stream", parts[1], None
        if parts and parts[0] == "sessions":
            if len(parts) == 1:
                return "sessions", None, None
            if len(parts) == 2:
                return "session", parts[1], None
            if len(parts) == 3:
                return "session", parts[1], parts[2]
        return "unknown", None, None

    # -- the guarded handler (routing + error mapping) ---------------------

    def _guard(self, req: Request, rid: int, obs, transport: str):
        kind, sid, verb = self._route(req)
        try:
            return self._handle(req, rid, obs, transport, kind, sid, verb)
        except wire.WireError as e:
            return json_response(400, {"error": str(e)})
        except KeyError:
            what = "ticket" if kind == "result" else "session"
            return json_response(404, {"error": f"no {what} {sid!r}"})
        except AdmissionReject as e:
            # admission backpressure (quota, session cap, shed): 429
            # with the unified structured body plus the tenant, and a
            # Retry-After sized to when the window actually frees
            payload = {"error": str(e), "tenant": e.tenant,
                       "request_id": rid}
            ctx = current_trace_context()
            if ctx is not None:
                payload["trace_id"] = ctx.trace_id
            resp = json_response(429, payload)
            resp.headers.append(retry_after_header(e.retry_after_s))
            return resp
        except TicketQueueFullError as e:
            # queue-full backpressure: same 503 body as before, now with
            # the Retry-After every backpressure rejection carries — one
            # dispatch round (plus slack) usually frees a slot
            payload = {"error": str(e), "request_id": rid}
            ctx = current_trace_context()
            if ctx is not None:
                payload["trace_id"] = ctx.trace_id
            resp = json_response(503, payload)
            resp.headers.append(retry_after_header(1.0))
            return resp
        except StorageDegradedError as e:
            # the storage plane is degraded and the --state-degrade
            # policy blocks this verb: same structured-503 contract as
            # every other backpressure answer, with Retry-After sized
            # to the persistence retry backoff — never a traceback
            payload = {"error": str(e), "persistence": "degraded",
                       "request_id": rid}
            ctx = current_trace_context()
            if ctx is not None:
                payload["trace_id"] = ctx.trace_id
            resp = json_response(503, payload)
            resp.headers.append(retry_after_header(e.retry_after_s))
            return resp
        except (DeadlineError, EngineUnavailableError,
                EngineStepError) as e:
            # fault-tolerance outcomes: the session survives; 503 tells
            # the client "try again / try later", never "you sent garbage"
            payload = {"error": str(e), "request_id": rid}
            ctx = current_trace_context()
            if ctx is not None:
                payload["trace_id"] = ctx.trace_id
            return json_response(503, payload)
        except (ConfigError, ValueError) as e:
            return json_response(400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — the structured-500 backstop
            # a bug must answer structured JSON on a live connection,
            # never a stock HTML traceback page.  The traceback goes to
            # stderr under the request id, not the wire.
            print(f"[mpi_tpu] request {rid}: unhandled "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            traceback.print_exc(file=sys.stderr)
            payload = {
                "error": f"internal server error ({type(e).__name__})",
                "request_id": rid,
            }
            ctx = current_trace_context()
            if ctx is not None:
                # the client-side half of log↔trace correlation: this id
                # keys GET /debug/trace/<trace_id> on any node
                payload["trace_id"] = ctx.trace_id
            if obs is not None:
                # flush the evidence: the ring (or live --trace-log)
                # holds the request's spans up to the failure point
                dump = obs.tracer.dump_on_crash(
                    f"request {rid}: {type(e).__name__}: {e}")
                if dump:
                    payload["trace_dump"] = dump
                    print(f"[mpi_tpu] request {rid}: trace dumped to "
                          f"{dump}", file=sys.stderr)
                fl = obs.flight
                if fl is not None:
                    # the flight ring rides the same crash evidence:
                    # the last N dispatches, attributed, land beside
                    # the trace dump
                    base = dump or os.path.join(
                        tempfile.gettempdir(),
                        f"mpi_tpu_trace_crash_{os.getpid()}.jsonl")
                    fdump = base + ".flights.jsonl"
                    try:
                        fl.dump(fdump)
                        payload["flight_dump"] = fdump
                    except OSError:
                        pass
            return json_response(500, payload)

    def _handle(self, req: Request, rid: int, obs, transport: str,
                kind: str, sid: Optional[str], verb: Optional[str]):
        mgr = self.manager
        method = req.method
        # body bound FIRST — before any read, any route work that might
        # read, and without trusting the route to exist (an oversized
        # body on a bogus path is still an oversized body)
        n = self._content_length(req)
        if n > self.max_body:
            return json_response(413, {
                "error": f"request body is {n} bytes; the server accepts "
                         f"at most {self.max_body} (--http-max-body)",
                "max_body": self.max_body,
            }, close=True)
        cluster = self.cluster
        forced_sid = None
        if cluster is not None:
            if kind == "cluster":
                return self._cluster_endpoint(req, method, verb, transport)
            if req.headers.get(FORWARDED_HEADER):
                # one hop max: a forwarded request is served HERE even
                # if routing views disagree — a stale table can cost a
                # 404, never a proxy loop
                forced_sid = req.headers.get(SESSION_ID_HEADER)
            else:
                routed, forced_sid = self._cluster_route(
                    req, transport, kind, sid, method)
                if routed is not None:
                    return routed
        if kind == "metrics" and method == "GET":
            if obs is None:
                return json_response(404, {
                    "error": "observability is disabled (--no-obs)"})
            if OPENMETRICS_MEDIA_TYPE in (req.headers.get("Accept") or ""):
                # negotiated upgrade only: exemplars ride OpenMetrics;
                # the default Prometheus text stays byte-identical
                text = obs.render_metrics(openmetrics=True)
                return Response(
                    200, text.encode("utf-8"),
                    f"{OPENMETRICS_MEDIA_TYPE}; version=1.0.0; "
                    f"charset=utf-8")
            text = obs.render_metrics()
            return Response(200, text.encode("utf-8"),
                            "text/plain; version=0.0.4; charset=utf-8")
        if kind == "trace" and method == "GET" and sid is not None:
            if obs is None:
                return json_response(404, {
                    "error": "observability is disabled (--no-obs)"})
            return self._trace_fetch(req, sid)
        if kind == "usage" and method == "GET":
            # same off-switch contract as /metrics: usage metering rides
            # the obs handle, so --no-obs answers the same structured 404
            if obs is None:
                return json_response(404, {
                    "error": "observability is disabled (--no-obs)"})
            return json_response(200, mgr.usage())
        if kind in ("slo", "timeseries") and method == "GET":
            # armed-only surfaces (ISSUE 15): --no-obs answers the usual
            # structured 404, and an instrumented-but-unarmed server
            # answers a 404 naming the flag — the endpoints exist only
            # when the sampler exists, mirroring the scrape's armed-only
            # slo families
            if obs is None:
                return json_response(404, {
                    "error": "observability is disabled (--no-obs)"})
            if obs.telemetry is None:
                return json_response(404, {
                    "error": "telemetry is not armed "
                             "(--telemetry-interval-s)"})
            if kind == "slo":
                return json_response(200, mgr.slo())
            return self._timeseries(req, obs.telemetry)
        if kind in ("flights", "anomalies") and method == "GET":
            # armed-only surfaces (ISSUE 19), same contract as /slo:
            # --no-obs answers the structured 404; an instrumented-but-
            # unarmed server answers a 404 naming the arming flag
            if obs is None:
                return json_response(404, {
                    "error": "observability is disabled (--no-obs)"})
            if kind == "flights":
                if obs.flight is None:
                    return json_response(404, {
                        "error": "flight recorder is not armed "
                                 "(--flight-recorder)"})
                return self._flights(req, obs.flight)
            if obs.anomaly is None:
                return json_response(404, {
                    "error": "anomaly detection is not armed "
                             "(--anomaly-detect)"})
            return json_response(200, obs.anomaly.snapshot())
        if kind == "profile" and method == "POST":
            return self._profile(req)
        if kind == "healthz" and method == "GET":
            health = mgr.health()
            # a draining node still SERVES (clients and proxy hops keep
            # working) but the probe answers 503 so load balancers
            # rotate it out; the payload says why
            code = (200 if health["ok"] and not health.get("draining")
                    else 503)
            return json_response(code, health)
        if kind == "stats" and method == "GET":
            return json_response(200, mgr.stats())
        if kind == "sessions" and method == "POST":
            body = self._body(req, transport)
            timeout_s = self._timeout_override(req, body)
            tenant = None
            if mgr.admission is not None:
                # tenancy binds at create: the header's tenant (default
                # when absent) owns the session, gated by its
                # concurrency cap inside the manager
                tenant = mgr.admission.resolve(
                    req.headers.get(TENANT_HEADER))
            out = mgr.create(body, timeout_s=timeout_s, sid=forced_sid,
                             tenant=tenant)
            if cluster is not None:
                cluster.record_route(out["id"])
            return json_response(200, out)
        if kind == "result" and method == "GET" and sid is not None:
            result = mgr.ticket_result(
                sid, wait=self._query_flag(req, "wait"),
                timeout_s=self._timeout_override(req, {}))
            if result.get("status") == "done" and self._wants_binary(req):
                # the ticket's outcome as one binary frame of the
                # session's CURRENT grid (which may be further along
                # than this ticket if later tickets already committed —
                # same read-your-ticket semantics as snapshot-after-wait)
                return self._binary_snapshot(result["id"], req, transport)
            return json_response(200, result)
        if kind == "stream" and method == "GET" and sid is not None:
            if transport != "aio":
                return json_response(501, {
                    "error": "streaming needs the selector front "
                             "(start the server with --front aio)"})
            session = mgr.get(sid)      # unknown session -> 404 at setup
            qs = parse_qs(urlsplit(req.path).query)
            raw = qs["every"][0] if "every" in qs else "1"
            try:
                every = int(raw)
            except (TypeError, ValueError):
                raise ConfigError(f"every must be an int, got {raw!r}")
            if every < 1:
                raise ConfigError(f"every must be >= 1, got {every}")
            window = self._viewport(req)
            if window is not None:
                # validate NOW so a bad viewport answers 400 at setup,
                # never a dead stream later
                cfg = session.config
                mgr.window_rects(window[0], window[1], window[2],
                                 window[3], cfg.rows, cfg.cols,
                                 cfg.boundary)
            delta = self._query_flag(req, "delta")
            raw_k = qs.get("keyframe_every", ["64"])[0]
            try:
                keyframe_every = int(raw_k)
            except (TypeError, ValueError):
                raise ConfigError(
                    f"keyframe_every must be an int, got {raw_k!r}")
            if keyframe_every < 1:
                raise ConfigError(
                    f"keyframe_every must be >= 1, got {keyframe_every}")
            return StreamPlan(sid, every, window=window, delta=delta,
                              keyframe_every=keyframe_every)
        if kind == "session" and sid is not None:
            if method == "POST" and verb == "step":
                body = self._body(req, transport)
                timeout_s = self._timeout_override(req, body)
                steps = body.get("steps", 1)
                if not isinstance(steps, int):
                    raise ConfigError(f"steps must be an int, got {steps!r}")
                # the admission decision runs BEFORE either step path —
                # an over-quota or shed request must never reach device
                # dispatch (no device_dispatch span, no ledger debit)
                qos = mgr.admission_check(
                    sid, steps,
                    tenant=req.headers.get(TENANT_HEADER),
                    qos=req.headers.get(CLASS_HEADER),
                ) if mgr.admission is not None else None
                if self._query_flag(req, "async") or bool(body.get("async")):
                    return json_response(200, mgr.step_async(
                        sid, steps, timeout_s=timeout_s, qos=qos))
                return json_response(
                    200, mgr.step(sid, steps, timeout_s=timeout_s))
            if method == "PUT" and verb == "board":
                return self._write_board(req, sid, transport)
            if method == "GET" and verb in ("snapshot", "board"):
                # /board is the windowed-read alias of /snapshot: both
                # accept ?x0=&y0=&h=&w= and serve O(viewport) bytes
                timeout_override = self._timeout_override(req, {})
                window = self._viewport(req)
                if window is not None:
                    return self._window_snapshot(
                        sid, req, transport, window,
                        timeout_s=timeout_override)
                if self._wants_binary(req):
                    return self._binary_snapshot(sid, req, transport,
                                                 timeout_s=timeout_override)
                grid, generation, config = mgr.snapshot_array(
                    sid, timeout_s=timeout_override)
                t0 = time.perf_counter()
                payload = {"id": sid, "generation": generation,
                           "rows": config.rows, "cols": config.cols,
                           "grid": format_grid_rows(grid)}
                body = json.dumps(payload).encode()
                self._observe_encode(t0, "json", transport)
                return Response(200, body, "application/json")
            if method == "GET" and verb == "density":
                return json_response(200, mgr.density(
                    sid, timeout_s=self._timeout_override(req, {})))
            if method == "DELETE" and verb is None:
                return json_response(200, mgr.close(
                    sid, timeout_s=self._timeout_override(req, {})))
        return json_response(404, {"error": f"no route {method} {req.path}"})

    # -- cluster routing (mpi_tpu/cluster; self.cluster is None outside
    # cluster mode and none of this runs) ----------------------------------

    def _cluster_endpoint(self, req: Request, method: str,
                          verb: Optional[str], transport: str) -> Response:
        cluster = self.cluster
        if verb == "gossip" and method == "POST":
            if cluster.inbound_cut("gossip"):
                # the inbound half of an injected partition: refuse the
                # digest exactly as a severed link would
                return json_response(503, {
                    "error": "gossip partition injected", "ok": False})
            applied = cluster.apply_digest(self._body(req, transport))
            # push-pull: the reply carries OUR digest, so one initiated
            # round synchronizes both directions
            return json_response(200, {"ok": True, "applied": applied,
                                       "digest": cluster.digest()})
        if verb == "join" and method == "POST":
            addr = self._body(req, transport).get("node")
            if not isinstance(addr, str) or not addr.strip():
                raise ConfigError("join body needs a 'node' address")
            try:
                return json_response(200, cluster.handle_join(addr))
            except ValueError as e:
                raise ConfigError(f"bad join address {addr!r}: {e}")
        if verb == "adopt" and method == "POST":
            sids = self._body(req, transport).get("sids")
            if not isinstance(sids, list):
                raise ConfigError("adopt body needs a 'sids' list")
            return json_response(200, cluster.handle_adopt(sids))
        if verb == "drain" and method == "POST":
            return json_response(200, cluster.drain())
        if verb is None and method == "GET":
            return json_response(200, cluster.info())
        return json_response(404, {"error": f"no route {method} {req.path}"})

    def _cluster_route(self, req: Request, transport: str, kind: str,
                       sid: Optional[str], method: str):
        """(response, forced_sid): a :class:`Response` when the request
        belongs to a peer (proxied, or its failure mapped), else None
        with the locally-allocated session id for the create path."""
        cluster = self.cluster
        if kind == "sessions" and method == "POST":
            # the receiving front allocates the id, THEN places it — so
            # the id's owner and the serving process always agree
            new_sid = cluster.new_session_id()
            owner = cluster.owner_addr(new_sid)
            if owner == cluster.id:
                return None, new_sid
            resp = self._proxy_to(owner, req, transport,
                                  extra={SESSION_ID_HEADER: new_sid},
                                  missing=("session", new_sid))
            if resp.code == 200:
                # the placement decision was made HERE — record it here
                # too, so the route outlives an owner that dies before
                # its first gossip round spreads it (failover adoption
                # scans the survivors' tables for the dead node's sids)
                cluster.record_route(new_sid, owner)
            return resp, None
        if kind in ("session", "stream") and sid is not None:
            owner = cluster.owner_addr(sid)
            if owner == cluster.id:
                return None, None
            if kind == "stream":
                # an open-ended push stream cannot be relayed hop-by-hop
                # without a parked thread per frame; redirect the client
                # to the owner instead
                return Response(
                    307, b"", "application/json",
                    headers=[("Location",
                              f"http://{owner}{req.path}")]), None
            return self._proxy_to(owner, req, transport,
                                  missing=("session", sid)), None
        if kind == "result" and sid is not None:
            owner = cluster.ticket_owner_addr(sid)
            if owner is not None:
                return self._proxy_to(owner, req, transport,
                                      missing=("ticket", sid)), None
            dead = cluster.dead_ticket_addr(sid)
            if dead is not None:
                # tickets are process-local and died with their owner;
                # answer the exact structured 404 without a doomed hop
                # (failover adoption restores sessions, never tickets)
                return json_response(404, {"error": f"no ticket {sid!r}",
                                           "peer": dead}), None
        return None, None

    def _proxy_to(self, owner: str, req: Request, transport: str,
                  extra: Optional[dict] = None,
                  missing: Optional[Tuple[str, str]] = None) -> Response:
        """Forward one request to ``owner`` and relay its response
        verbatim (the peer's structured errors ARE the answer)."""
        cluster = self.cluster
        raw = self._raw_body(req, transport)
        headers = {FORWARDED_HEADER: cluster.id}
        for name in ("Content-Type", "Accept", TENANT_HEADER, CLASS_HEADER):
            # tenancy must survive the hop: the owning node runs the
            # admission decision, and it needs the caller's headers
            value = req.headers.get(name)
            if value:
                headers[name] = value
        if raw:
            headers["Content-Length"] = str(len(raw))
        headers.update(extra or {})
        if self.obs is not None:
            # the hop is itself a span; the traceparent sent carries ITS
            # id, so the owner's http_request stitches under this hop
            with self.obs.span("proxy_hop", peer=owner, method=req.method,
                               path=req.path) as sp:
                ctx = current_trace_context()
                if ctx is not None:
                    headers[TRACEPARENT_HEADER] = format_traceparent(ctx)
                resp = self._proxy_send(owner, req, raw, headers, missing)
                sp.tag(code=resp.code)
            return resp
        return self._proxy_send(owner, req, raw, headers, missing)

    def _proxy_send(self, owner: str, req: Request, raw: bytes,
                    headers: dict,
                    missing: Optional[Tuple[str, str]]) -> Response:
        """One proxy hop, hardened: idempotent verbs (GET — snapshots,
        ticket reads) retry ``--proxy-retries`` times with doubling
        backoff before giving up; non-idempotent ones fail after the
        first attempt (a retried step could double-commit).  The final
        503 carries ``Retry-After`` sized to the gossip interval — by
        then either the peer answered a heartbeat or failover has begun
        re-homing its sessions."""
        cluster = self.cluster
        attempts = 1 + (cluster.proxy_retries if req.method == "GET" else 0)
        err: Optional[PeerUnreachable] = None
        for attempt in range(attempts):
            if attempt:
                time.sleep(cluster.proxy_backoff_s * (2 ** (attempt - 1)))
            try:
                cluster.net_fault("proxy", owner)
                status, ctype, data = proxy_request(
                    owner, req.method, req.path, raw, headers,
                    timeout_s=cluster.proxy_timeout_s)
                return Response(status, data, ctype)
            except PeerUnreachable as e:
                err = e
        what, ident = missing or ("resource", "?")
        if what == "ticket":
            # the 404-after-restart ticket contract extended across
            # the slice: a dead owner's tickets answer the same
            # structured 404 a restarted single process would
            return json_response(404, {"error": f"no ticket {ident!r}",
                                       "peer": owner})
        resp = json_response(503, {"error": str(err), "peer": owner})
        resp.headers = [("Retry-After",
                         str(max(1, math.ceil(cluster.interval_s))))]
        return resp

    # -- telemetry history (GET /debug/timeseries) -------------------------

    def _timeseries(self, req: Request, tel) -> Response:
        """``?series=&window=`` over the recorder's rings: no ``series``
        lists what is recorded; with one, counters render as rates and
        gauges raw, timestamps monotone non-decreasing by construction
        (samples append in clock order)."""
        from mpi_tpu.obs.timeseries import WINDOW_S

        qs = parse_qs(urlsplit(req.path).query)
        window = qs.get("window", ["5m"])[0]
        if window not in WINDOW_S:
            raise ConfigError(
                f"window must be one of {sorted(WINDOW_S)}, "
                f"got {window!r}")
        name = qs.get("series", [None])[0]
        if name is None:
            return json_response(200, {
                "series": tel.series_names(),
                "windows": sorted(WINDOW_S, key=WINDOW_S.get),
                "interval_s": tel.interval_s,
                "stats": tel.stats(),
            })
        if name not in tel.KINDS:
            return json_response(404, {
                "error": f"no series {name!r}",
                "series": tel.series_names()})
        return json_response(200, {
            "series": name,
            "kind": tel.KINDS[name],
            "window": window,
            "interval_s": tel.interval_s,
            "points": tel.points(name, WINDOW_S[window]),
        })

    # -- dispatch flight records (GET /debug/flights) ----------------------

    def _flights(self, req: Request, flight) -> Response:
        """``?session=&signature=&slower_than=&trace=&limit=`` over the
        flight ring (oldest first after filtering).  ``trace`` matches a
        record's own trace id or any of its batch-rider links."""
        qs = parse_qs(urlsplit(req.path).query)
        session = qs.get("session", [None])[0]
        signature = qs.get("signature", [None])[0]
        trace = qs.get("trace", [None])[0]
        slower = qs.get("slower_than", [None])[0]
        if slower is not None:
            try:
                slower = float(slower)
            except (TypeError, ValueError):
                raise ConfigError(
                    f"slower_than must be a number, got {slower!r}")
        raw_limit = qs.get("limit", [None])[0]
        limit = None
        if raw_limit is not None:
            try:
                limit = int(raw_limit)
            except (TypeError, ValueError):
                raise ConfigError(
                    f"limit must be an int, got {raw_limit!r}")
        records = flight.snapshot(session=session, signature=signature,
                                  slower_than=slower, trace=trace,
                                  limit=limit)
        return json_response(200, {
            "stats": flight.stats(),
            "count": len(records),
            "flights": records,
        })

    # -- distributed trace assembly (GET /debug/trace/<trace_id>) ----------

    def _trace_fragment(self, trace_id: str) -> List[dict]:
        """This process's spans for one trace, node-stamped so stitched
        output says where each span ran.  Shared dispatch rounds carry
        riders as ``links`` (``trace_id:span_id``), not parents — a
        round linked to this trace is part of the story, so it rides
        along (it stitches as a root: related, unparented)."""
        node = self.cluster.id if self.cluster is not None else "local"
        prefix = trace_id + ":"
        out = []
        for rec in self.obs.tracer.snapshot():
            if rec.get("trace_id") == trace_id or any(
                    link.startswith(prefix)
                    for link in rec.get("links") or ()):
                rec["node"] = node
                out.append(rec)
        return out

    def _trace_fetch(self, req: Request, trace_id: str) -> Response:
        """Assemble one trace: the local fragment, plus (in cluster
        mode, when this request was not itself a fan-out hop) each live
        peer's fragment, stitched into one wall-clock-ordered tree.  A
        peer that is down — or dies mid-fetch — lands in ``partial``;
        the fetch itself never fails on a dead peer."""
        cluster = self.cluster
        forwarded = bool(req.headers.get(FORWARDED_HEADER))
        fanout = cluster is not None and not forwarded
        with self.obs.span("trace_fetch", target=trace_id, fanout=fanout):
            spans = self._trace_fragment(trace_id)
            nodes = [self.cluster.id if cluster is not None else "local"]
            partial: List[str] = []
            if fanout:
                for addr, state in cluster.health_block()["peers"].items():
                    if (not state["alive"]
                            and state["last_seen_age_s"] is not None):
                        # known-dead by heartbeat age: report, don't wait
                        # on a connect timeout
                        partial.append(addr)
                        continue
                    try:
                        status, _, data = proxy_request(
                            addr, "GET", f"/debug/trace/{trace_id}", b"",
                            {FORWARDED_HEADER: cluster.id},
                            timeout_s=cluster.timeout_s)
                        frag = json.loads(data) if status == 200 else None
                    except (PeerUnreachable, ValueError):
                        frag = None
                    if not isinstance(frag, dict):
                        partial.append(addr)
                        continue
                    spans.extend(s for s in frag.get("spans", [])
                                 if isinstance(s, dict))
                    nodes.append(addr)
            ordered, roots = stitch_spans(spans)
        return json_response(200, {
            "trace_id": trace_id,
            "nodes": nodes,
            "partial": partial,
            "complete": not partial,
            "spans": ordered,
            "tree": roots,
        })

    # -- wire-format helpers -----------------------------------------------

    def _observe_encode(self, t0: float, fmt: str, transport: str) -> None:
        if self.obs is not None:
            self.obs.wire_encode.observe(time.perf_counter() - t0,
                                         format=fmt, transport=transport)

    def _observe_decode(self, t0: float, fmt: str, transport: str) -> None:
        if self.obs is not None:
            self.obs.wire_decode.observe(time.perf_counter() - t0,
                                         format=fmt, transport=transport)

    def _binary_snapshot(self, sid: str, req: Request, transport: str,
                         timeout_s: Optional[float] = None) -> Response:
        grid, generation, config = self.manager.snapshot_array(
            sid, timeout_s=timeout_s)
        t0 = time.perf_counter()
        frame = self.encode_grid_frame(grid, generation, config)
        self._observe_encode(t0, "binary", transport)
        return Response(200, frame, wire.GRID_MEDIA_TYPE)

    def encode_grid_frame(self, grid, generation, config) -> bytes:
        """One binary frame for a session grid (snapshot, ticket result,
        and the aio front's stream pushes all come through here)."""
        return wire.encode_frame(grid, generation=generation,
                                 rule=config.rule, boundary=config.boundary)

    def _window_snapshot(self, sid: str, req: Request, transport: str,
                         window: Tuple[int, int, int, int],
                         timeout_s: Optional[float] = None) -> Response:
        """One viewport read: O(viewport) device bytes (per-shard
        fetch inside the manager) and O(viewport) wire bytes (a v2
        windowed frame, or the JSON window shape)."""
        x0, y0, h, w = window
        grid, generation, config = self.manager.snapshot_window(
            sid, x0, y0, h, w, timeout_s=timeout_s)
        if self._wants_binary(req):
            t0 = time.perf_counter()
            frame = wire.encode_window_frame(
                grid, x0=x0, y0=y0,
                board_shape=(config.rows, config.cols),
                generation=generation, rule=config.rule,
                boundary=config.boundary)
            self._observe_encode(t0, "binary", transport)
            if self.obs is not None:
                self.obs.viewport_bytes.inc(len(frame),
                                            transport=transport)
            return Response(200, frame, wire.GRID_MEDIA_TYPE)
        t0 = time.perf_counter()
        payload = {"id": sid, "generation": generation,
                   "board_rows": config.rows, "board_cols": config.cols,
                   "x0": x0, "y0": y0, "rows": h, "cols": w,
                   "grid": format_grid_rows(grid)}
        body = json.dumps(payload).encode()
        self._observe_encode(t0, "json", transport)
        if self.obs is not None:
            self.obs.viewport_bytes.inc(len(body), transport=transport)
        return Response(200, body, "application/json")

    def _write_board(self, req: Request, sid: str,
                     transport: str) -> Response:
        window = None
        if self._sends_binary(req):
            raw = self._raw_body(req, transport)
            t0 = time.perf_counter()
            grid, meta = wire.decode_frame(raw)
            self._observe_decode(t0, "binary", transport)
            if meta["is_delta"]:
                raise ConfigError(
                    "board writes take full or windowed frames, "
                    "not delta frames")
            if meta["window"] is not None:
                wx0, wy0, _, _ = meta["window"]
                window = (wx0, wy0)
            generation = (meta["generation"] if meta["has_generation"]
                          else None)
            timeout_s = self._timeout_override(req, {})
        else:
            body = self._body(req, transport)
            timeout_s = self._timeout_override(req, body)
            if "grid" not in body:
                raise ConfigError('board write needs a "grid" key '
                                  "(or a binary frame body)")
            t0 = time.perf_counter()
            grid = parse_grid_rows(body["grid"])
            self._observe_decode(t0, "json", transport)
            x0, y0 = body.get("x0"), body.get("y0")
            if (x0 is None) != (y0 is None):
                raise ConfigError(
                    "a region write needs both x0 and y0")
            if x0 is not None:
                if not isinstance(x0, int) or not isinstance(y0, int):
                    raise ConfigError(
                        f"x0/y0 must be ints, got {x0!r}/{y0!r}")
                window = (x0, y0)
            generation = body.get("generation")
            if generation is not None and not isinstance(generation, int):
                raise ConfigError(
                    f"generation must be an int, got {generation!r}")
        if window is not None:
            return json_response(200, self.manager.write_window(
                sid, window[0], window[1], grid, generation=generation,
                timeout_s=timeout_s))
        return json_response(200, self.manager.write_board(
            sid, grid, generation=generation, timeout_s=timeout_s))

    def _profile(self, req: Request) -> Response:
        logdir = self.profile_dir
        if logdir is None:
            return json_response(404, {
                "error": "profiling is disabled "
                         "(start the server with --profile-dir)"})
        qs = parse_qs(urlsplit(req.path).query)
        raw = qs["secs"][0] if "secs" in qs else "1"
        try:
            secs = float(raw)
        except (TypeError, ValueError):
            raise ConfigError(f"secs must be a number, got {raw!r}")
        from mpi_tpu.obs.profile import run_profile

        result = run_profile(logdir, secs)
        return json_response(200 if result["ok"] else 503, result)
