"""Threaded stdlib HTTP front end (``http.server``, the default).

PR 7 moved the request semantics — routing, validation, manager verbs,
error mapping, wire-format negotiation, observability — into
:class:`~mpi_tpu.serve.transport.AppCore`; this module is the thin
transport that remains: one ``BaseHTTPRequestHandler`` that packages
each request into a :class:`~mpi_tpu.serve.transport.Request`, calls
``core.dispatch``, and writes the :class:`Response` back.  The bytes on
the wire for every JSON route are unchanged from PR 6 (same payload
construction, same ``json.dumps``, same header sequence) — gated by
``tools/obs_smoke.py``.

Routes, error shapes, deadline overrides, and the binary grid protocol
are documented on :mod:`mpi_tpu.serve.transport` (one doc, N fronts).
The one route this front cannot serve is ``GET /stream/<sid>`` — a
blocking thread per open-ended stream is exactly the thread-per-idle-
client model the selectors front (``serve/aio.py``, ``--front aio``)
exists to replace — so the core answers it a structured 501 here.

The server is a ``ThreadingHTTPServer`` — requests against different
boards run concurrently; the per-session locks in ``session.py``
serialize requests against the same board, and concurrent
same-signature step requests are coalesced into stacked batched
dispatches by ``serve/batch.py``.
"""

from __future__ import annotations

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from mpi_tpu.serve.session import SessionManager
from mpi_tpu.serve.transport import AppCore, DEFAULT_MAX_BODY, Request


class _Handler(BaseHTTPRequestHandler):
    # the core is attached to the *server* by make_server; handlers are
    # constructed per request
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: D102 — quiet by default
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    def _run(self, method: str) -> None:
        core: AppCore = self.server.core
        req = Request(method, self.path, self.headers, self.rfile.read)
        resp = core.dispatch(req, transport="threaded")
        self.send_response(resp.code)
        self.send_header("Content-Type", resp.content_type)
        self.send_header("Content-Length", str(len(resp.body)))
        for name, value in resp.headers:
            self.send_header(name, value)
        if resp.close:
            # an unread request body (the 413 path) poisons keep-alive
            # framing: tell the client and drop the connection
            self.send_header("Connection", "close")
            self.close_connection = True
        self.end_headers()
        self.wfile.write(resp.body)

    # -- verbs -------------------------------------------------------------

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        self._run("GET")

    def do_POST(self):  # noqa: N802
        self._run("POST")

    def do_PUT(self):  # noqa: N802
        self._run("PUT")

    def do_DELETE(self):  # noqa: N802
        self._run("DELETE")


def make_server(host: str = "127.0.0.1", port: int = 0,
                manager: Optional[SessionManager] = None,
                verbose: bool = False,
                profile_dir: Optional[str] = None,
                max_body: int = DEFAULT_MAX_BODY) -> ThreadingHTTPServer:
    """A ready-to-run server (not yet serving — call ``serve_forever`` or
    drive it from a thread; ``port=0`` binds an ephemeral port, which the
    tests use).  The bound address is ``server.server_address``.
    Observability rides on the manager: ``manager.obs`` (or None) decides
    whether ``/metrics`` serves and spans record; ``profile_dir`` arms
    ``POST /debug/profile``."""
    server = ThreadingHTTPServer((host, port), _Handler)
    server.core = AppCore(manager, verbose=verbose, profile_dir=profile_dir,
                          max_body=max_body)
    # kept as server attributes too — tests and tools reach for these
    server.manager = server.core.manager
    server.verbose = verbose
    server.request_ids = server.core.request_ids
    server.obs = server.core.obs
    server.profile_dir = profile_dir
    return server
