"""Stdlib-only HTTP front end (``http.server`` + JSON, no new deps).

Routes (all bodies and responses are JSON):

    POST   /sessions                   create a board (spec in body)
    POST   /sessions/<id>/step         advance; body {"steps": k}, default 1.
                                       {"async": true} in the body (or
                                       ?async=1) enqueues instead and
                                       answers {"ticket": ..., "status":
                                       "pending"} immediately
    GET    /result/<ticket>            the ticket's outcome: pending, done
                                       (with the step result), or the SAME
                                       structured 503/404 the blocking path
                                       would have answered; ?wait=1 blocks
                                       until resolution (request budget)
    GET    /sessions/<id>/snapshot     full grid as '0'/'1' row strings
    GET    /sessions/<id>/density      live-cell count / density
    DELETE /sessions/<id>              close the board
    GET    /healthz                    liveness probe
    GET    /stats                      cache counters + per-session throughput
                                       + microbatch occupancy/amortization
                                       (the ``batch`` section, when enabled)
    GET    /metrics                    Prometheus text exposition (the one
                                       non-JSON route; 404 when the manager
                                       runs with obs disabled)
    POST   /debug/profile?secs=N       capture a jax.profiler device trace
                                       over live traffic (requires
                                       --profile-dir; one capture at a time)

Observability (PR 4): every request's id is entered into the obs
request-id contextvar for its whole handling, so spans recorded anywhere
downstream — session lock waits, batched dispatches on the leader's
thread, checkpoint writes, watchdog workers — carry the same id as the
``http_request`` span and the access-log line.  The catch-all 500
additionally dumps the trace ring to disk (or points at the live
``--trace-log``) so the evidence for a crash report survives the
process.

Errors: 400 with {"error": ...} for bad specs/bodies (``ConfigError``/
``ValueError``), 404 for unknown sessions and routes, 503 for fault-
tolerance outcomes (deadline exceeded, breaker open with degradation
disabled, retries exhausted — the session survives all three), and a
catch-all 500 with ``{"error": ..., "request_id": N}`` for anything
unexpected: a bug must answer structured JSON on a live connection,
never ``http.server``'s stock HTML traceback page.  Every request gets
a server-unique id; verbose mode logs it with the outcome line and the
500 path prints the traceback to stderr under the same id, so a client
report ("request 1041 gave me a 500") lines up with the server log.

Per-request deadline override: ``?timeout_s=SECONDS`` on any session
verb (or a ``timeout_s`` body key on step/create) overrides the
server-wide ``--request-timeout-s``; ``timeout_s=0`` disables the
budget for that request.

The server is a ``ThreadingHTTPServer`` — requests against different
boards run concurrently; the per-session locks in ``session.py``
serialize requests against the same board, and concurrent
same-signature step requests are coalesced into stacked batched
dispatches by ``serve/batch.py``.
"""

from __future__ import annotations

import itertools
import json
import sys
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from mpi_tpu.config import ConfigError
from mpi_tpu.obs.trace import reset_request_id, set_request_id
from mpi_tpu.serve.session import (
    DeadlineError, EngineStepError, EngineUnavailableError, SessionManager,
    TicketQueueFullError,
)


class _Handler(BaseHTTPRequestHandler):
    # the manager is attached to the *server* by make_server; handlers are
    # constructed per request
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: D102 — quiet by default
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    # -- plumbing ----------------------------------------------------------

    def _reply(self, code: int, payload: dict) -> None:
        self._reply_bytes(code, json.dumps(payload).encode(),
                          "application/json")

    def _reply_text(self, code: int, text: str, content_type: str) -> None:
        self._reply_bytes(code, text.encode("utf-8"), content_type)

    def _reply_bytes(self, code: int, body: bytes,
                     content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
        self._last_code = code          # the http_request span's code tag
        if getattr(self.server, "verbose", False):
            print(f"[mpi_tpu] request {getattr(self, '_rid', '?')}: "
                  f"{self.command} {self.path} -> {code}", file=sys.stderr)

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length") or 0)
        if n == 0:
            return {}
        try:
            data = json.loads(self.rfile.read(n) or b"{}")
        except json.JSONDecodeError as e:
            raise ConfigError(f"request body is not valid JSON: {e}")
        if not isinstance(data, dict):
            raise ConfigError("request body must be a JSON object")
        return data

    def _timeout_override(self, body: dict) -> Optional[float]:
        """The request's explicit deadline override, or None to use the
        server default: ``?timeout_s=`` wins over a ``timeout_s`` body
        key.  (It is a transport parameter, not part of the board spec —
        the create body's strict key check never sees it.)"""
        qs = parse_qs(urlsplit(self.path).query)
        raw = qs["timeout_s"][0] if "timeout_s" in qs else body.pop(
            "timeout_s", None)
        if raw is None:
            return None
        try:
            return float(raw)
        except (TypeError, ValueError):
            raise ConfigError(f"timeout_s must be a number, got {raw!r}")

    def _query_flag(self, name: str) -> bool:
        """A boolean query parameter (``?async=1``, ``?wait=true``)."""
        qs = parse_qs(urlsplit(self.path).query)
        return (qs.get(name, ["0"])[0].lower() in ("1", "true", "yes"))

    def _route(self) -> Tuple[str, Optional[str], Optional[str]]:
        """(kind, session_id, verb) from the path."""
        parts = [p for p in self.path.split("?")[0].split("/") if p]
        if parts == ["healthz"]:
            return "healthz", None, None
        if parts == ["stats"]:
            return "stats", None, None
        if parts == ["metrics"]:
            return "metrics", None, None
        if parts == ["debug", "profile"]:
            return "profile", None, None
        if len(parts) == 2 and parts[0] == "result":
            return "result", parts[1], None     # parts[1] is the ticket id
        if parts and parts[0] == "sessions":
            if len(parts) == 1:
                return "sessions", None, None
            if len(parts) == 2:
                return "session", parts[1], None
            if len(parts) == 3:
                return "session", parts[1], parts[2]
        return "unknown", None, None

    def _dispatch(self, method: str) -> None:
        rid = next(self.server.request_ids)
        self._rid = rid                     # _reply's verbose outcome line
        self._last_code = 0
        obs = getattr(self.server, "obs", None)
        if obs is None:
            return self._handle(method, rid, None)
        # one shared id per request: every span recorded while this
        # request is being handled — in this thread, in the watchdog
        # worker (context copied), in the batch leader (entry.rid) —
        # carries it, which is what makes the JSONL reconstructable
        token = set_request_id(rid)
        try:
            with obs.span("http_request", method=method,
                          path=self.path) as sp:
                self._handle(method, rid, obs)
                sp.tag(code=self._last_code)
            obs.http_requests.inc(method=method, code=self._last_code)
        finally:
            reset_request_id(token)

    def _handle(self, method: str, rid: int, obs) -> None:
        mgr: SessionManager = self.server.manager
        kind, sid, verb = self._route()
        try:
            if kind == "metrics" and method == "GET":
                if obs is None:
                    return self._reply(404, {
                        "error": "observability is disabled (--no-obs)"})
                return self._reply_text(
                    200, obs.render_metrics(),
                    "text/plain; version=0.0.4; charset=utf-8")
            if kind == "profile" and method == "POST":
                return self._profile()
            if kind == "healthz" and method == "GET":
                health = mgr.health()
                return self._reply(200 if health["ok"] else 503, health)
            if kind == "stats" and method == "GET":
                return self._reply(200, mgr.stats())
            if kind == "sessions" and method == "POST":
                body = self._body()
                timeout_s = self._timeout_override(body)
                return self._reply(200, mgr.create(body, timeout_s=timeout_s))
            if kind == "result" and method == "GET" and sid is not None:
                return self._reply(200, mgr.ticket_result(
                    sid, wait=self._query_flag("wait"),
                    timeout_s=self._timeout_override({})))
            if kind == "session" and sid is not None:
                if method == "POST" and verb == "step":
                    body = self._body()
                    timeout_s = self._timeout_override(body)
                    steps = body.get("steps", 1)
                    if not isinstance(steps, int):
                        raise ConfigError(f"steps must be an int, got {steps!r}")
                    if self._query_flag("async") or bool(body.get("async")):
                        return self._reply(200, mgr.step_async(
                            sid, steps, timeout_s=timeout_s))
                    return self._reply(
                        200, mgr.step(sid, steps, timeout_s=timeout_s))
                if method == "GET" and verb == "snapshot":
                    return self._reply(200, mgr.snapshot(
                        sid, timeout_s=self._timeout_override({})))
                if method == "GET" and verb == "density":
                    return self._reply(200, mgr.density(
                        sid, timeout_s=self._timeout_override({})))
                if method == "DELETE" and verb is None:
                    return self._reply(200, mgr.close(
                        sid, timeout_s=self._timeout_override({})))
            return self._reply(404, {"error": f"no route {method} {self.path}"})
        except KeyError:
            what = "ticket" if kind == "result" else "session"
            return self._reply(404, {"error": f"no {what} {sid!r}"})
        except (DeadlineError, EngineUnavailableError, EngineStepError,
                TicketQueueFullError) as e:
            # fault-tolerance outcomes: the session survives; 503 tells
            # the client "try again / try later", never "you sent garbage"
            return self._reply(503, {"error": str(e), "request_id": rid})
        except (ConfigError, ValueError) as e:
            return self._reply(400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — the structured-500 backstop
            # without this, http.server answers an HTML traceback page and
            # drops the connection; a JSON API must fail in JSON.  The
            # traceback goes to stderr under the request id, not the wire.
            print(f"[mpi_tpu] request {rid}: unhandled "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            traceback.print_exc(file=sys.stderr)
            payload = {
                "error": f"internal server error ({type(e).__name__})",
                "request_id": rid,
            }
            if obs is not None:
                # flush the evidence: the ring (or live --trace-log)
                # holds the request's spans up to the failure point
                dump = obs.tracer.dump_on_crash(
                    f"request {rid}: {type(e).__name__}: {e}")
                if dump:
                    payload["trace_dump"] = dump
                    print(f"[mpi_tpu] request {rid}: trace dumped to "
                          f"{dump}", file=sys.stderr)
            return self._reply(500, payload)

    def _profile(self) -> None:
        logdir = getattr(self.server, "profile_dir", None)
        if logdir is None:
            return self._reply(404, {
                "error": "profiling is disabled "
                         "(start the server with --profile-dir)"})
        qs = parse_qs(urlsplit(self.path).query)
        raw = qs["secs"][0] if "secs" in qs else "1"
        try:
            secs = float(raw)
        except (TypeError, ValueError):
            raise ConfigError(f"secs must be a number, got {raw!r}")
        from mpi_tpu.obs.profile import run_profile

        result = run_profile(logdir, secs)
        return self._reply(200 if result["ok"] else 503, result)

    # -- verbs -------------------------------------------------------------

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        self._dispatch("GET")

    def do_POST(self):  # noqa: N802
        self._dispatch("POST")

    def do_DELETE(self):  # noqa: N802
        self._dispatch("DELETE")


def make_server(host: str = "127.0.0.1", port: int = 0,
                manager: Optional[SessionManager] = None,
                verbose: bool = False,
                profile_dir: Optional[str] = None) -> ThreadingHTTPServer:
    """A ready-to-run server (not yet serving — call ``serve_forever`` or
    drive it from a thread; ``port=0`` binds an ephemeral port, which the
    tests use).  The bound address is ``server.server_address``.
    Observability rides on the manager: ``manager.obs`` (or None) decides
    whether ``/metrics`` serves and spans record; ``profile_dir`` arms
    ``POST /debug/profile``."""
    server = ThreadingHTTPServer((host, port), _Handler)
    server.manager = manager if manager is not None else SessionManager()
    server.verbose = verbose
    server.request_ids = itertools.count(1)
    server.obs = server.manager.obs
    server.profile_dir = profile_dir
    return server
