"""Stdlib-only HTTP front end (``http.server`` + JSON, no new deps).

Routes (all bodies and responses are JSON):

    POST   /sessions                   create a board (spec in body)
    POST   /sessions/<id>/step         advance; body {"steps": k}, default 1
    GET    /sessions/<id>/snapshot     full grid as '0'/'1' row strings
    GET    /sessions/<id>/density      live-cell count / density
    DELETE /sessions/<id>              close the board
    GET    /healthz                    liveness probe
    GET    /stats                      cache counters + per-session throughput
                                       + microbatch occupancy/amortization
                                       (the ``batch`` section, when enabled)

Errors: 400 with {"error": ...} for bad specs/bodies (``ConfigError``/
``ValueError``), 404 for unknown sessions and routes.  The server is a
``ThreadingHTTPServer`` — requests against different boards run
concurrently; the per-session locks in ``session.py`` serialize requests
against the same board, and concurrent same-signature step requests are
coalesced into stacked batched dispatches by ``serve/batch.py``.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from mpi_tpu.config import ConfigError
from mpi_tpu.serve.session import SessionManager


class _Handler(BaseHTTPRequestHandler):
    # the manager is attached to the *server* by make_server; handlers are
    # constructed per request
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: D102 — quiet by default
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    # -- plumbing ----------------------------------------------------------

    def _reply(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length") or 0)
        if n == 0:
            return {}
        try:
            data = json.loads(self.rfile.read(n) or b"{}")
        except json.JSONDecodeError as e:
            raise ConfigError(f"request body is not valid JSON: {e}")
        if not isinstance(data, dict):
            raise ConfigError("request body must be a JSON object")
        return data

    def _route(self) -> Tuple[str, Optional[str], Optional[str]]:
        """(kind, session_id, verb) from the path."""
        parts = [p for p in self.path.split("?")[0].split("/") if p]
        if parts == ["healthz"]:
            return "healthz", None, None
        if parts == ["stats"]:
            return "stats", None, None
        if parts and parts[0] == "sessions":
            if len(parts) == 1:
                return "sessions", None, None
            if len(parts) == 2:
                return "session", parts[1], None
            if len(parts) == 3:
                return "session", parts[1], parts[2]
        return "unknown", None, None

    def _dispatch(self, method: str) -> None:
        mgr: SessionManager = self.server.manager
        kind, sid, verb = self._route()
        try:
            if kind == "healthz" and method == "GET":
                return self._reply(200, {"ok": True, "sessions": len(mgr)})
            if kind == "stats" and method == "GET":
                return self._reply(200, mgr.stats())
            if kind == "sessions" and method == "POST":
                return self._reply(200, mgr.create(self._body()))
            if kind == "session" and sid is not None:
                if method == "POST" and verb == "step":
                    steps = self._body().get("steps", 1)
                    if not isinstance(steps, int):
                        raise ConfigError(f"steps must be an int, got {steps!r}")
                    return self._reply(200, mgr.step(sid, steps))
                if method == "GET" and verb == "snapshot":
                    return self._reply(200, mgr.snapshot(sid))
                if method == "GET" and verb == "density":
                    return self._reply(200, mgr.density(sid))
                if method == "DELETE" and verb is None:
                    return self._reply(200, mgr.close(sid))
            return self._reply(404, {"error": f"no route {method} {self.path}"})
        except KeyError:
            return self._reply(404, {"error": f"no session {sid!r}"})
        except (ConfigError, ValueError) as e:
            return self._reply(400, {"error": str(e)})

    # -- verbs -------------------------------------------------------------

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        self._dispatch("GET")

    def do_POST(self):  # noqa: N802
        self._dispatch("POST")

    def do_DELETE(self):  # noqa: N802
        self._dispatch("DELETE")


def make_server(host: str = "127.0.0.1", port: int = 0,
                manager: Optional[SessionManager] = None,
                verbose: bool = False) -> ThreadingHTTPServer:
    """A ready-to-run server (not yet serving — call ``serve_forever`` or
    drive it from a thread; ``port=0`` binds an ephemeral port, which the
    tests use).  The bound address is ``server.server_address``."""
    server = ThreadingHTTPServer((host, port), _Handler)
    server.manager = manager if manager is not None else SessionManager()
    server.verbose = verbose
    return server
