"""Deterministic fault injection at the engine dispatch boundary.

Every recovery path in the serve layer — retry with backoff, the
circuit breaker, host-backend degradation, the dispatch watchdog —
exists because a device dispatch can raise, hang, or stall.  None of
those failures can be produced on demand by real hardware in a unit
test, so this module fakes them *deterministically*: a
:class:`FaultPlan` parsed from ``--inject-faults SPEC`` (or the
``MPI_TPU_FAULTS`` env var) decides, purely from the dispatch ordinal,
whether the Nth engine dispatch raises :class:`InjectedFault`, hangs
(sleeps, then raises — the step must never half-commit), or delays
(sleeps, then proceeds normally).

The hook point is :meth:`mpi_tpu.backends.tpu.Engine.step` /
``step_batched``: the serve layer installs
:meth:`FaultInjector.engine_hook` on every engine it hands to a
session, so faults fire exactly where a sick TPU would — after compile,
before the device call, with the session's grid still intact.  (Real
failures can also corrupt the donated input buffer; the degradation
path never trusts the device grid for exactly that reason — it replays
from the last checkpoint instead.)

The cluster layer (``mpi_tpu/cluster``) hooks the same plans at its two
network seams: ``gossip`` (one outbound digest send per peer per round)
and ``proxy`` (one outbound forwarded-request attempt, retries
included).  Network sites get network modes — ``drop`` severs that one
attempt (the caller sees the peer as unreachable), ``delay`` sleeps
then proceeds, and ``partition`` drops outbound *and* cuts inbound at
the same site (:meth:`FaultInjector.inbound_cut`) while the clause
still covers the next outbound ordinal — a deterministic, symmetric
network split that heals exactly when the clause range is spent.

The storage plane (``serve/recovery.py``) hooks the same plans at its
single IO choke point, :meth:`StateStore._io`: ``io-write`` (one
buffered write of a record envelope or journal entry), ``io-fsync``
(the flush+fsync making it durable), and ``io-replace`` (the atomic
rename publishing a record).  IO sites get IO modes — ``raise`` fails
the call with ``EIO``, ``enospc`` fails it with ``ENOSPC`` (the
full-disk path), ``delay`` sleeps then proceeds, and ``torn:frac``
makes the write stop after ``frac`` of its bytes *and actually flushes
the torn prefix to disk* before failing — the exact on-disk shape a
crash mid-write leaves, which is what the CRC envelopes and journal
tail-truncation exist to survive.

Spec grammar (comma-separated clauses; a leading ``seed=N`` clause
seeds the probabilistic selector)::

    SPEC   := [ 'seed=' int ',' ] clause ( ',' clause )*
    clause := site ':' sel ':' mode [ ':' arg ]
    site   := 'step' | 'batched' | 'any' | 'gossip' | 'proxy'
            | 'io-write' | 'io-fsync' | 'io-replace'
    sel    := N | N'+' | N'-'M | '*' | 'p'FLOAT
    mode   := 'raise' | 'hang' | 'delay'          (engine sites)
            | 'drop' | 'delay' | 'partition'      (network sites)
            | 'raise' | 'torn' | 'enospc' | 'delay'   (io sites)

``sel`` counts dispatches at that site from 1 (``any`` counts both
engine sites together; network and io sites each count alone): ``3``
fires on exactly the 3rd dispatch, ``3+`` from the 3rd on, ``2-4`` on
the 2nd through 4th, ``*`` on every one, and ``p0.25`` on each with
probability 0.25 drawn from a ``random.Random`` seeded by the plan's
``seed=`` clause (default 0) — same seed, same dispatch order, same
faults, every run.  ``arg`` is seconds for ``hang``/``delay`` (defaults
30 and 0.05) and the byte fraction in [0, 1] for ``torn`` (default
0.5); ``raise``, ``drop``, ``partition``, and ``enospc`` ignore it.

Examples::

    --inject-faults 'step:1-3:raise'       # first three solo dispatches fail
    --inject-faults 'any:2:hang:5'         # 2nd dispatch wedges for 5 s
    --inject-faults 'seed=7,step:p0.1:raise'
    --inject-faults 'gossip:1-8:partition' # both gossip directions cut until
                                           # 8 outbound sends have been eaten
    --inject-faults 'proxy:1:drop'         # first proxy hop fails (retry path)
    --inject-faults 'io-write:2:torn:0.25' # 2nd write stops at 25% of bytes
    --inject-faults 'io-fsync:1+:enospc'   # the disk is full from here on
"""

from __future__ import annotations

import errno
import random
import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

from mpi_tpu.config import ConfigError

_ENGINE_SITES = ("step", "batched", "any")
_NET_SITES = ("gossip", "proxy")
_IO_SITES = ("io-write", "io-fsync", "io-replace")
_SITES = _ENGINE_SITES + _NET_SITES + _IO_SITES
_ENGINE_MODES = ("raise", "hang", "delay")
_NET_MODES = ("drop", "delay", "partition")
_IO_MODES = ("raise", "torn", "enospc", "delay")
_MODES = ("raise", "hang", "delay", "drop", "partition", "torn", "enospc")
_DEFAULT_SECONDS = {"raise": 0.0, "hang": 30.0, "delay": 0.05,
                    "drop": 0.0, "partition": 0.0,
                    "torn": 0.5, "enospc": 0.0}


class InjectedFault(RuntimeError):
    """The error a 'raise' (or an ended 'hang') fault throws — a stand-in
    for whatever a sick device dispatch would have raised."""


class InjectedNetworkFault(RuntimeError):
    """What a 'drop' or 'partition' clause throws at a network site —
    the cluster layer maps it to ``PeerUnreachable``, so an injected
    split exercises exactly the real unreachable-peer paths."""


class InjectedIOFault(OSError):
    """What an io-site clause throws — an ``OSError`` with a real errno
    (``EIO`` for raise/torn, ``ENOSPC`` for enospc), so the storage
    plane's degradation machinery cannot special-case injected failures
    apart from kernel ones."""

    def __init__(self, eno: int, msg: str):
        super().__init__(eno, msg)


@dataclass(frozen=True)
class _Clause:
    site: str                       # step | batched | any
    lo: Optional[int]               # 1-based dispatch range [lo, hi]
    hi: Optional[int]               # None with lo=None means probabilistic
    prob: Optional[float]
    mode: str                       # raise | hang | delay
    seconds: float

    def matches(self, nth: int, draw: Optional[float]) -> bool:
        if self.prob is not None:
            return draw is not None and draw < self.prob
        if self.lo is None:
            return True                             # '*'
        return self.lo <= nth <= (self.hi if self.hi is not None else nth)


class FaultPlan:
    """Parsed, immutable fault spec; :class:`FaultInjector` executes it."""

    def __init__(self, clauses: List[_Clause], seed: int = 0,
                 spec: str = ""):
        self.clauses = tuple(clauses)
        self.seed = seed
        self.spec = spec

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        clauses, seed = [], 0
        for raw in str(spec).split(","):
            raw = raw.strip()
            if not raw:
                continue
            if raw.startswith("seed="):
                try:
                    seed = int(raw[5:])
                except ValueError:
                    raise ConfigError(f"bad fault seed clause {raw!r}")
                continue
            parts = raw.split(":")
            if len(parts) not in (3, 4):
                raise ConfigError(
                    f"bad fault clause {raw!r}; want site:sel:mode[:seconds]")
            site, sel, mode = parts[0], parts[1], parts[2]
            if site not in _SITES:
                raise ConfigError(
                    f"bad fault site {site!r}; one of {_SITES}")
            if mode not in _MODES:
                raise ConfigError(
                    f"bad fault mode {mode!r}; one of {_MODES}")
            allowed = (_NET_MODES if site in _NET_SITES
                       else _IO_MODES if site in _IO_SITES
                       else _ENGINE_MODES)
            if mode not in allowed:
                raise ConfigError(
                    f"fault mode {mode!r} is not valid at site {site!r}; "
                    f"one of {allowed}")
            lo = hi = prob = None
            try:
                if sel == "*":
                    pass
                elif sel.startswith("p"):
                    prob = float(sel[1:])
                    if not 0.0 <= prob <= 1.0:
                        raise ValueError
                elif sel.endswith("+"):
                    lo, hi = int(sel[:-1]), None
                elif "-" in sel:
                    a, b = sel.split("-")
                    lo, hi = int(a), int(b)
                else:
                    lo = hi = int(sel)
                if lo is not None and lo < 1:
                    raise ValueError
            except ValueError:
                raise ConfigError(
                    f"bad fault selector {sel!r}; want N, N+, N-M, *, or pF")
            try:
                seconds = (float(parts[3]) if len(parts) == 4
                           else _DEFAULT_SECONDS[mode])
            except ValueError:
                raise ConfigError(f"bad fault seconds in {raw!r}")
            if seconds < 0:
                raise ConfigError(f"fault seconds must be >= 0 in {raw!r}")
            if mode == "torn" and not 0.0 <= seconds <= 1.0:
                raise ConfigError(
                    f"torn fraction must be in [0, 1] in {raw!r}")
            clauses.append(_Clause(site, lo, hi, prob, mode, seconds))
        if not clauses:
            raise ConfigError(f"fault spec {spec!r} has no clauses")
        return cls(clauses, seed=seed, spec=str(spec))


class FaultInjector:
    """Executes a :class:`FaultPlan` against the live dispatch stream.

    Thread-safe: the counter/RNG advance under a lock, the sleep and the
    raise happen outside it (a hanging fault must wedge only its own
    dispatch, not the injector).  One injector serves every engine in
    the process — the serve layer installs :meth:`engine_hook` as
    ``Engine.fault_hook`` on each engine it creates or reuses."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self._counts = {"step": 0, "batched": 0, "any": 0,
                        "gossip": 0, "proxy": 0,
                        "io-write": 0, "io-fsync": 0, "io-replace": 0}
        self._rng = random.Random(plan.seed)
        self.injected = {"raise": 0, "hang": 0, "delay": 0,
                         "drop": 0, "partition": 0,
                         "torn": 0, "enospc": 0}

    @classmethod
    def from_spec(cls, spec: str) -> "FaultInjector":
        return cls(FaultPlan.parse(spec))

    def engine_hook(self, site: str) -> None:
        """Called by the engine immediately before a device dispatch;
        ``site`` is 'step' or 'batched'.  Raises :class:`InjectedFault`
        (raise/hang modes) or returns after an optional delay."""
        action: Optional[Tuple[str, float, str]] = None
        with self._lock:
            self._counts[site] += 1
            self._counts["any"] += 1
            for c in self.plan.clauses:
                if c.site not in (site, "any"):
                    continue
                nth = self._counts[c.site if c.site != "any" else "any"]
                draw = self._rng.random() if c.prob is not None else None
                if c.matches(nth, draw):
                    action = (c.mode, c.seconds,
                              f"injected {c.mode} at {site} dispatch "
                              f"#{self._counts[site]}")
                    self.injected[c.mode] += 1
                    break
        if action is None:
            return
        mode, seconds, msg = action
        if mode == "delay":
            time.sleep(seconds)
            return
        if mode == "hang":
            # sleep out the hang, then FAIL: the dispatch must never
            # half-commit a step the client was already told timed out
            time.sleep(seconds)
        raise InjectedFault(msg)

    def net_hook(self, site: str, peer: str = "?") -> None:
        """Called by the cluster layer immediately before an outbound
        network attempt; ``site`` is 'gossip' or 'proxy'.  Raises
        :class:`InjectedNetworkFault` (drop/partition) or returns after
        an optional delay — same counter-under-lock, effect-outside-lock
        discipline as :meth:`engine_hook`."""
        action: Optional[Tuple[str, float, str]] = None
        with self._lock:
            self._counts[site] += 1
            nth = self._counts[site]
            for c in self.plan.clauses:
                if c.site != site:
                    continue
                draw = self._rng.random() if c.prob is not None else None
                if c.matches(nth, draw):
                    action = (c.mode, c.seconds,
                              f"injected {c.mode} at {site} attempt "
                              f"#{nth} (peer {peer})")
                    self.injected[c.mode] += 1
                    break
        if action is None:
            return
        mode, seconds, msg = action
        if mode == "delay":
            time.sleep(seconds)
            return
        raise InjectedNetworkFault(msg)

    def io_hook(self, site: str) -> Optional[float]:
        """Called by :meth:`StateStore._io` immediately before a storage
        syscall; ``site`` is 'io-write', 'io-fsync', or 'io-replace'.
        Raises :class:`InjectedIOFault` (raise → ``EIO``, enospc →
        ``ENOSPC``), sleeps through a delay, or returns the torn byte
        fraction for the store to execute (the tear must happen at the
        write itself so the torn prefix really lands on disk) — None
        means proceed normally.  Same counter-under-lock,
        effect-outside-lock discipline as the other hooks."""
        action: Optional[Tuple[str, float, str]] = None
        with self._lock:
            self._counts[site] += 1
            nth = self._counts[site]
            for c in self.plan.clauses:
                if c.site != site:
                    continue
                draw = self._rng.random() if c.prob is not None else None
                if c.matches(nth, draw):
                    action = (c.mode, c.seconds,
                              f"injected {c.mode} at {site} call #{nth}")
                    self.injected[c.mode] += 1
                    break
        if action is None:
            return None
        mode, seconds, msg = action
        if mode == "delay":
            time.sleep(seconds)
            return None
        if mode == "torn":
            return seconds              # the byte fraction to keep
        if mode == "enospc":
            raise InjectedIOFault(errno.ENOSPC, msg)
        raise InjectedIOFault(errno.EIO, msg)

    def inbound_cut(self, site: str) -> bool:
        """True while a ``partition`` clause at ``site`` still covers
        the NEXT outbound ordinal — inbound refusal tracks the same
        deterministic window as outbound drops, so the split is
        symmetric and heals exactly when the clause range is spent.
        (Probabilistic partition clauses never cut inbound: there is no
        ordinal to anchor the draw to.)"""
        with self._lock:
            nxt = self._counts.get(site, 0) + 1
            for c in self.plan.clauses:
                if (c.site == site and c.mode == "partition"
                        and c.prob is None and c.matches(nxt, None)):
                    return True
        return False

    def stats(self) -> dict:
        with self._lock:
            return {
                "spec": self.plan.spec,
                "seed": self.plan.seed,
                "dispatches": dict(self._counts),
                "injected": dict(self.injected),
            }
