"""Board sessions: device-resident state between requests.

A session is one live board — created once (paying setup: plan + compile
on a cache miss, nearly nothing on a hit), then stepped/inspected by any
number of requests.  The backend dispatch mirrors ``cli.py``'s: the same
four backends, the same engine semantics, so a board served over HTTP is
bit-identical to the same config run one-shot (the parity tests in
``tests/test_serve.py`` hold the serve path to the ``serial_np`` oracle
exactly like the batch CLI's parity suite).

Sessions and engines are decoupled: TPU sessions hold a *reference* to a
cached :class:`~mpi_tpu.backends.tpu.Engine` plus their own grid buffer,
so N boards of the same shape share one compiled stepper.  Eviction from
the :class:`~mpi_tpu.serve.cache.EngineCache` only drops the cache's
reference — live sessions keep theirs.

Stepping routes through the :class:`~mpi_tpu.serve.batch.MicroBatcher`
(when enabled, the default): concurrent same-signature same-depth steps
coalesce into one stacked ``Engine.step_batched`` dispatch — B boards
pay ONE ~68 ms tunnel dispatch instead of B (PERF.md) — while lone
requests, host backends, and any batched-path failure take the solo
path, so batching only ever removes dispatches, never changes results.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import numpy as np

from mpi_tpu.config import ConfigError, GolConfig, plan_signature
from mpi_tpu.models.rules import rule_from_name
from mpi_tpu.serve.batch import MicroBatcher
from mpi_tpu.serve.cache import EngineCache

_SPEC_KEYS = {
    "rows", "cols", "rule", "boundary", "backend", "seed", "comm_every",
    "overlap", "mesh", "segments",
}


def _parse_spec(spec: dict):
    """(GolConfig, segments) from a create-request JSON body.  Strict on
    key names — a typoed knob silently falling back to its default is the
    worst failure mode a service API can have."""
    if not isinstance(spec, dict):
        raise ConfigError(f"session spec must be a JSON object, got {type(spec).__name__}")
    unknown = set(spec) - _SPEC_KEYS
    if unknown:
        raise ConfigError(
            f"unknown session keys {sorted(unknown)}; allowed: {sorted(_SPEC_KEYS)}"
        )
    try:
        rows = int(spec["rows"])
        cols = int(spec["cols"])
    except KeyError as e:
        raise ConfigError(f"session spec needs {e.args[0]!r}")
    mesh = spec.get("mesh")
    if isinstance(mesh, str):
        try:
            a, b = mesh.lower().split("x")
            mesh = (int(a), int(b))
        except ValueError:
            raise ConfigError(f"mesh must look like 2x4, got {mesh!r}")
    elif mesh is not None:
        try:
            a, b = mesh
            mesh = (int(a), int(b))
        except (TypeError, ValueError):
            raise ConfigError(f"mesh must be 'IxJ' or [i, j], got {mesh!r}")
    segments = spec.get("segments", [1])
    try:
        segments = sorted({int(n) for n in segments if int(n) > 0})
    except (TypeError, ValueError):
        raise ConfigError(f"segments must be a list of ints, got {spec.get('segments')!r}")
    config = GolConfig(
        rows=rows,
        cols=cols,
        steps=0,                       # sessions step on demand, not by plan
        seed=int(spec.get("seed", 0)),
        rule=rule_from_name(str(spec.get("rule", "life"))),
        boundary=str(spec.get("boundary", "periodic")),
        backend=str(spec.get("backend", "tpu")),
        mesh_shape=mesh,
        comm_every=int(spec.get("comm_every", 1)),
        overlap=bool(spec.get("overlap", False)),
    )
    return config, segments


class Session:
    """One live board.  ``engine`` is set for tpu sessions (grid is a
    device array); host backends keep a numpy grid and a ``stepper(grid,
    n) -> grid`` closure instead.  All mutation goes through ``lock`` —
    the HTTP server is threaded and two requests against one board must
    serialize (two requests against two boards must not)."""

    def __init__(self, sid: str, config: GolConfig, *, engine=None,
                 stepper=None, grid=None, cache_hit: bool = False,
                 setup_s: float = 0.0, plan_sig=None):
        self.id = sid
        self.config = config
        self.engine = engine
        self.stepper = stepper
        self.grid = grid
        self.cache_hit = cache_hit
        self.plan_sig = plan_sig        # batch-queue key (tpu sessions)
        self.generation = 0
        self.batched_steps = 0          # steps served by a coalesced batch
        self.setup_s = setup_s          # plan + compile (grows if a step
        self.steady_s = 0.0             # needs a new depth); stepping time
        self.lock = threading.Lock()
        self.closed = False

    def throughput(self) -> dict:
        gens = self.generation
        cells = self.config.cells
        return {
            "generations": gens,
            "steady_s": round(self.steady_s, 6),
            "setup_s": round(self.setup_s, 6),
            "gens_per_s": (gens / self.steady_s) if self.steady_s > 0 else None,
            "cell_updates_per_s": (gens * cells / self.steady_s)
            if self.steady_s > 0 else None,
        }


class SessionManager:
    """Owns the session table, the engine cache, and the microbatcher.

    Single-host by design (multi-host serving is a ROADMAP open item):
    snapshot/density fetch through ``Engine.fetch``/``population``, which
    assume one process can address the whole array.

    ``batching=False`` (or ``batch_window_ms=0`` with no concurrency)
    degenerates to the PR-1 solo behavior; engine-backed steps otherwise
    route through the :class:`~mpi_tpu.serve.batch.MicroBatcher`.
    """

    def __init__(self, cache: Optional[EngineCache] = None, *,
                 batching: bool = True, batch_window_ms: float = 2.0,
                 batch_max: int = 8):
        self.cache = cache if cache is not None else EngineCache()
        self.batcher = (
            MicroBatcher(window_ms=batch_window_ms, max_batch=batch_max)
            if batching else None
        )
        self._sessions: Dict[str, Session] = {}
        self._lock = threading.Lock()
        self._next = 0

    # -- lifecycle ---------------------------------------------------------

    def create(self, spec: dict) -> dict:
        config, segments = _parse_spec(spec)
        t0 = time.perf_counter()
        if config.backend == "tpu":
            session = self._create_tpu(config, segments)
        else:
            session = self._create_host(config)
        session.setup_s = time.perf_counter() - t0
        with self._lock:
            self._next += 1
            session.id = f"s{self._next}"
            self._sessions[session.id] = session
        info = self.describe(session)
        info["cache"] = self.cache.stats()
        return info

    def _create_tpu(self, config: GolConfig, segments) -> Session:
        from mpi_tpu.backends.tpu import build_engine, device_count
        from mpi_tpu.parallel.mesh import choose_mesh_shape, make_mesh

        mesh_shape = config.mesh_shape or choose_mesh_shape(device_count())
        sig = plan_signature(config, mesh_shape, segments)
        engine, hit = self.cache.get_or_build(
            sig, lambda: build_engine(config, mesh=make_mesh(mesh_shape)))
        grid = engine.init_grid(seed=config.seed)
        # precompile the requested segment set (a no-op on a cache hit —
        # the signature pins the set, so the hit engine already has it)
        engine.compile_segments(grid, segments)
        return Session("?", config, engine=engine, grid=grid, cache_hit=hit,
                       plan_sig=sig)

    def _create_host(self, config: GolConfig) -> Session:
        from mpi_tpu.utils.hashinit import init_tile_np

        rule, boundary = config.rule, config.boundary
        if config.backend == "serial":
            from mpi_tpu.backends.serial_np import evolve_np

            def stepper(g, n):
                return evolve_np(g, n, rule, boundary)
        elif config.backend == "cpp":
            from mpi_tpu.backends.cpp import evolve_cpp, load_library

            load_library()              # build/dlopen is setup, like compile

            def stepper(g, n):
                return evolve_cpp(g, n, rule, boundary)
        else:  # cpp-par
            from mpi_tpu.backends.cpp import (
                evolve_par_cpp, load_library, plan_tiles,
            )

            load_library()
            tiles = plan_tiles((config.rows, config.cols), config.workers,
                               rule.radius)

            def stepper(g, n):
                return evolve_par_cpp(g, n, rule, boundary, tiles=tiles)

        grid = init_tile_np(config.rows, config.cols, config.seed)
        return Session("?", config, stepper=stepper, grid=grid)

    def close(self, sid: str) -> dict:
        with self._lock:
            session = self._sessions.pop(sid, None)
        if session is None:
            raise KeyError(sid)
        with session.lock:
            session.closed = True
            session.grid = None         # free device/host buffers now; the
            session.engine = None       # cached engine survives for reuse
        return {"id": sid, "closed": True}

    def get(self, sid: str) -> Session:
        with self._lock:
            session = self._sessions.get(sid)
        if session is None:
            raise KeyError(sid)
        return session

    # -- verbs -------------------------------------------------------------

    def step(self, sid: str, steps: int = 1) -> dict:
        if steps < 1:
            raise ConfigError(f"steps must be >= 1, got {steps}")
        session = self.get(sid)
        if self.batcher is not None and session.engine is not None \
                and session.plan_sig is not None:
            # engine-backed steps coalesce: concurrent same-signature
            # same-depth requests share ONE stacked device dispatch; the
            # batcher takes session.lock (leader-side) and falls back to
            # _step_locked when alone or on any batched-path failure
            return self.batcher.submit(self, session, steps)
        with session.lock:
            if session.closed:
                raise KeyError(sid)
            return self._step_locked(session, steps)

    def _step_locked(self, session: Session, steps: int) -> dict:
        """The solo step body; caller holds ``session.lock`` (the HTTP
        path via :meth:`step`, the microbatch leader for lone/fallback
        entries)."""
        if session.engine is not None:
            import jax

            # a depth never seen before compiles here — that is setup,
            # not stepping; charge it to setup_s so throughput numbers
            # stay honest (same accounting as run_tpu's phases)
            t0 = time.perf_counter()
            session.engine.ensure_compiled(session.grid, steps)
            t1 = time.perf_counter()
            session.setup_s += t1 - t0
            # step donates the input buffer: replace the reference
            grid = session.engine.step(session.grid, steps)
            jax.block_until_ready(grid)
            session.grid = grid
            session.steady_s += time.perf_counter() - t1
        else:
            t0 = time.perf_counter()
            session.grid = session.stepper(session.grid, steps)
            session.steady_s += time.perf_counter() - t0
        session.generation += steps
        return {"id": session.id, "generation": session.generation,
                "steps": steps}

    def snapshot(self, sid: str) -> dict:
        session = self.get(sid)
        with session.lock:
            if session.closed:
                raise KeyError(sid)
            # generation must be captured with the grid, INSIDE the lock —
            # a concurrent step between fetch and return would otherwise
            # label this grid with a later generation (torn read)
            generation = session.generation
            if session.engine is not None:
                grid = session.engine.fetch(session.grid)
                if grid is None:
                    raise ConfigError(
                        "snapshot over HTTP needs single-host execution")
            else:
                grid = session.grid
        rows = ["".join("1" if v else "0" for v in row) for row in
                np.asarray(grid, dtype=np.uint8)]
        return {"id": sid, "generation": generation,
                "rows": session.config.rows, "cols": session.config.cols,
                "grid": rows}

    def density(self, sid: str) -> dict:
        session = self.get(sid)
        with session.lock:
            if session.closed:
                raise KeyError(sid)
            # same torn-read discipline as snapshot: the generation and
            # the population it describes leave the lock together
            generation = session.generation
            if session.engine is not None:
                pop = session.engine.population(session.grid)
            else:
                pop = int(np.asarray(session.grid, dtype=np.int64).sum())
        return {"id": sid, "generation": generation,
                "population": pop,
                "density": pop / session.config.cells}

    # -- introspection -----------------------------------------------------

    def describe(self, session: Session) -> dict:
        # snapshot every field under session.lock: a concurrent close()
        # nulls session.engine, and a concurrent step bumps generation —
        # reading them unlocked can tear (engine checked non-None, then
        # dereferenced as None)
        with session.lock:
            engine = session.engine
            d = {
                "id": session.id,
                "backend": session.config.backend,
                "rows": session.config.rows,
                "cols": session.config.cols,
                "rule": str(session.config.rule),
                "boundary": session.config.boundary,
                "generation": session.generation,
                "throughput": session.throughput(),
            }
            if engine is not None:
                d["cache_hit"] = session.cache_hit
                d["engine_compiles"] = engine.compile_count
                d["engine_batched_compiles"] = engine.batched_compile_count
                d["engine_notes"] = list(engine.notes)
                d["batched_steps"] = session.batched_steps
        return d

    def stats(self) -> dict:
        with self._lock:
            sessions = list(self._sessions.values())
        out = {
            "cache": self.cache.stats(),
            "sessions": [self.describe(s) for s in sessions],
        }
        if self.batcher is not None:
            out["batch"] = self.batcher.stats()
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)
