"""Board sessions: device-resident state between requests.

A session is one live board — created once (paying setup: plan + compile
on a cache miss, nearly nothing on a hit), then stepped/inspected by any
number of requests.  The backend dispatch mirrors ``cli.py``'s: the same
four backends, the same engine semantics, so a board served over HTTP is
bit-identical to the same config run one-shot (the parity tests in
``tests/test_serve.py`` hold the serve path to the ``serial_np`` oracle
exactly like the batch CLI's parity suite).

Sessions and engines are decoupled: TPU sessions hold a *reference* to a
cached :class:`~mpi_tpu.backends.tpu.Engine` plus their own grid buffer,
so N boards of the same shape share one compiled stepper.  Eviction from
the :class:`~mpi_tpu.serve.cache.EngineCache` only drops the cache's
reference — live sessions keep theirs.

Stepping routes through the :class:`~mpi_tpu.serve.batch.MicroBatcher`
(when enabled, the default): concurrent same-signature same-depth steps
coalesce into one stacked ``Engine.step_batched`` dispatch — B boards
pay ONE ~68 ms tunnel dispatch instead of B (PERF.md) — while lone
requests, host backends, and any batched-path failure take the solo
path, so batching only ever removes dispatches, never changes results.

Fault tolerance (PR 3) wraps the whole step path:

* **Deadlines** — every verb accepts a time budget
  (``request_timeout_s`` default, per-request override); engine
  dispatches run inside a *watchdog* worker thread, so a hung
  ``block_until_ready`` becomes a :class:`DeadlineError` (HTTP 503)
  while the HTTP handler thread walks free.  The wedged worker holds the
  session lock until the device call ends; every later request against
  that board times out cleanly instead of piling up.
* **Retry + circuit breaker** — transient engine failures retry with
  bounded exponential backoff inside the request's budget; consecutive
  failures are counted per plan signature in the
  :class:`~mpi_tpu.serve.cache.EngineCache` breaker, and once it opens
  the affected sessions *degrade*: their board is rebuilt by
  deterministic replay (seed or last checkpoint → ``serial_np`` oracle,
  bit-identical by PARITY.md) and served by the host stepper.  Results
  stay exact; only throughput degrades.  With degradation disabled an
  open breaker answers :class:`EngineUnavailableError` (HTTP 503).
* **Checkpoint/restore** — with a ``state_dir``, every committed step
  persists the session record (crash-safe, ``serve/recovery.py``) and a
  packed grid snapshot every ``checkpoint_every`` generations; a new
  manager over the same dir rebuilds every session by replay,
  bit-identical to an uninterrupted run.

Async ticketed stepping (PR 5, ``serve/ticket.py``) is opt-in per
request: :meth:`SessionManager.step_async` enqueues a ticket whose
budget starts at enqueue and whose eventual outcome —
:meth:`SessionManager.ticket_result` — carries the same
deadline/breaker/watchdog semantics as the blocking verbs.  The
dispatch loop decomposes depth-k tickets into unit steps so mixed-depth
sessions share batched dispatches; the sync path (``async`` absent) is
untouched and stays bit-identical to the pre-async code.
"""

from __future__ import annotations

import contextlib
import contextvars
import sys
import threading
import time
from typing import Dict, Optional

import numpy as np

from mpi_tpu.backends.serial_np import evolve_np
from mpi_tpu.config import ConfigError, GolConfig, plan_signature
from mpi_tpu.models.rules import rule_from_name
from mpi_tpu.serve import recovery
from mpi_tpu.serve.batch import MicroBatcher
from mpi_tpu.serve.cache import EngineCache, signature_label
from mpi_tpu.serve.ticket import AsyncDispatcher, TicketQueueFullError
from mpi_tpu.utils.hashinit import init_tile_np

_SPEC_KEYS = {
    "rows", "cols", "rule", "boundary", "backend", "seed", "comm_every",
    "overlap", "mesh", "segments", "sparse_tile",
}


def _span(obs, name, **fields):
    """A trace span when observability is on, a no-op context otherwise —
    the guard every instrumentation site in this module goes through, so
    ``obs=None`` runs the pre-obs code path exactly."""
    if obs is None:
        return contextlib.nullcontext()
    return obs.span(name, **fields)


class DeadlineError(RuntimeError):
    """The request's time budget ran out (a slow or hung dispatch, or a
    board wedged behind one).  Maps to HTTP 503; the session survives."""


class EngineUnavailableError(RuntimeError):
    """The plan signature's circuit breaker is open and degradation is
    disabled — there is nothing left to serve the request with (503)."""


class EngineStepError(RuntimeError):
    """An engine step failed and retries were exhausted without tripping
    the breaker (503; the client may retry — the breaker is counting)."""


def _parse_spec(spec: dict):
    """(GolConfig, segments) from a create-request JSON body.  Strict on
    key names — a typoed knob silently falling back to its default is the
    worst failure mode a service API can have."""
    if not isinstance(spec, dict):
        raise ConfigError(f"session spec must be a JSON object, got {type(spec).__name__}")
    unknown = set(spec) - _SPEC_KEYS
    if unknown:
        raise ConfigError(
            f"unknown session keys {sorted(unknown)}; allowed: {sorted(_SPEC_KEYS)}"
        )
    try:
        rows = int(spec["rows"])
        cols = int(spec["cols"])
    except KeyError as e:
        raise ConfigError(f"session spec needs {e.args[0]!r}")
    mesh = spec.get("mesh")
    if isinstance(mesh, str):
        try:
            a, b = mesh.lower().split("x")
            mesh = (int(a), int(b))
        except ValueError:
            raise ConfigError(f"mesh must look like 2x4, got {mesh!r}")
    elif mesh is not None:
        try:
            a, b = mesh
            mesh = (int(a), int(b))
        except (TypeError, ValueError):
            raise ConfigError(f"mesh must be 'IxJ' or [i, j], got {mesh!r}")
    segments = spec.get("segments", [1])
    try:
        segments = sorted({int(n) for n in segments if int(n) > 0})
    except (TypeError, ValueError):
        raise ConfigError(f"segments must be a list of ints, got {spec.get('segments')!r}")
    config = GolConfig(
        rows=rows,
        cols=cols,
        steps=0,                       # sessions step on demand, not by plan
        seed=int(spec.get("seed", 0)),
        rule=rule_from_name(str(spec.get("rule", "life"))),
        boundary=str(spec.get("boundary", "periodic")),
        backend=str(spec.get("backend", "tpu")),
        mesh_shape=mesh,
        comm_every=int(spec.get("comm_every", 1)),
        overlap=bool(spec.get("overlap", False)),
        sparse_tile=int(spec.get("sparse_tile", 0)),
    )
    return config, segments


def format_grid_rows(grid) -> list:
    """The JSON snapshot's grid encoding — one '0'/'1' string per row.
    Shared with the transport layer (``serve/transport.py``) so the
    JSON and binary wire paths format from the same fetched array and
    can never drift."""
    return ["".join("1" if v else "0" for v in row)
            for row in np.asarray(grid, dtype=np.uint8)]


def parse_grid_rows(rows) -> np.ndarray:
    """Inverse of :func:`format_grid_rows` for board writes: a list of
    '0'/'1' strings (or of 0/1 int lists) to a uint8 array.  Ragged or
    non-binary input is a :class:`ConfigError` (HTTP 400)."""
    if not isinstance(rows, list) or not rows:
        raise ConfigError("grid must be a non-empty list of rows")
    try:
        arr = np.array([[int(c) for c in row] for row in rows],
                       dtype=np.uint8)
    except (TypeError, ValueError) as e:
        raise ConfigError(f"grid rows must be '0'/'1' strings or 0/1 "
                          f"lists: {e}")
    if arr.ndim != 2:
        raise ConfigError("grid rows must all have the same length")
    if arr.max(initial=0) > 1:
        raise ConfigError("grid cells must be 0 or 1")
    return arr


def _normalize_timeout(timeout_s: Optional[float]) -> Optional[float]:
    """The one timeout convention, in one place: ``None`` means "no
    explicit value" and any ``<= 0`` means "disable the budget" — both
    normalize to ``None``.  Every budget entry point (manager default,
    create, the blocking verbs via ``_budget``, ticket enqueue) goes
    through here so the convention cannot drift between paths."""
    if timeout_s is not None and timeout_s <= 0:
        return None
    return timeout_s


class _Deadline:
    """A monotonic countdown; ``seconds=None`` never expires."""

    __slots__ = ("t0", "seconds")

    def __init__(self, seconds: Optional[float]):
        self.t0 = time.monotonic()
        self.seconds = None if seconds is None else max(0.0, float(seconds))

    def remaining(self) -> Optional[float]:
        if self.seconds is None:
            return None
        return max(0.0, self.seconds - (time.monotonic() - self.t0))

    def expired(self) -> bool:
        r = self.remaining()
        return r is not None and r <= 0


def _watchdog_call(fn, deadline: _Deadline, label: str):
    """Run ``fn`` under the dispatch watchdog: with no budget it runs
    inline (zero overhead, the pre-PR-3 path); with one, it runs in a
    daemon worker thread and a timeout raises :class:`DeadlineError` in
    the caller while the worker is *abandoned* — Python threads cannot
    be killed, but an abandoned worker merely finishes (or wedges) in
    the background holding the session lock, which later requests see as
    their own clean deadline timeouts rather than a stuck handler."""
    budget = deadline.remaining()
    if budget is None:
        return fn()
    box = {}
    done = threading.Event()
    # carry the caller's context (the per-request id contextvar) into the
    # worker, so spans recorded under the watchdog still tag the request
    ctx = contextvars.copy_context()

    def run():
        try:
            box["result"] = ctx.run(fn)
        except BaseException as e:  # noqa: BLE001 — re-raised in the caller
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=run, daemon=True, name=f"watchdog:{label}")
    t.start()
    if not done.wait(budget):
        raise DeadlineError(
            f"{label} exceeded its {deadline.seconds:.3g}s budget "
            f"(dispatch abandoned to the watchdog; the session survives)")
    if "error" in box:
        raise box["error"]
    return box["result"]


class Session:
    """One live board.  ``engine`` is set for tpu sessions (grid is a
    device array); host backends keep a numpy grid and a ``stepper(grid,
    n) -> grid`` closure instead.  All mutation goes through ``lock`` —
    the HTTP server is threaded and two requests against one board must
    serialize (two requests against two boards must not)."""

    def __init__(self, sid: str, config: GolConfig, *, engine=None,
                 stepper=None, grid=None, cache_hit: bool = False,
                 setup_s: float = 0.0, plan_sig=None):
        self.id = sid
        self.config = config
        self.engine = engine
        self.stepper = stepper
        self.grid = grid
        self.cache_hit = cache_hit
        self.plan_sig = plan_sig        # batch-queue key (tpu sessions)
        self.generation = 0
        self.batched_steps = 0          # steps served by a coalesced batch
        self.setup_s = setup_s          # plan + compile (grows if a step
        self.steady_s = 0.0             # needs a new depth); stepping time
        self.lock = threading.Lock()
        self.closed = False
        # fault-tolerance state
        self.spec: Optional[dict] = None    # normalized create body (persistence)
        self.ckpt: Optional[dict] = None    # last encoded grid snapshot
        self.degraded = False               # serving via serial_np fallback
        self.degraded_reason: Optional[str] = None
        self.restored = False               # rebuilt by replay after restart
        self.last_error: Optional[str] = None
        # admission-control tags (ISSUE 16): the owning tenant and the
        # tenant-default priority class.  Both stay None on an unarmed
        # server — describe() and the batch key then behave exactly as
        # before admission existed.
        self.tenant: Optional[str] = None
        self.qos: Optional[str] = None

    def throughput(self) -> dict:  # lint: disable=lock-discipline -- scrape-time racy read: plain attribute loads, atomic under the GIL
        gens = self.generation
        cells = self.config.cells
        return {
            "generations": gens,
            "steady_s": round(self.steady_s, 6),
            "setup_s": round(self.setup_s, 6),
            "gens_per_s": (gens / self.steady_s) if self.steady_s > 0 else None,
            "cell_updates_per_s": (gens * cells / self.steady_s)
            if self.steady_s > 0 else None,
        }


class SessionManager:
    """Owns the session table, the engine cache, the microbatcher, and
    (PR 3) the fault-tolerance machinery: the state store, the fault
    injector, the per-signature breakers (in the cache), and the
    degradation path.

    Single-host by design (multi-host serving is a ROADMAP open item):
    snapshot/density fetch through ``Engine.fetch``/``population``, which
    assume one process can address the whole array.

    ``batching=False`` (or ``batch_window_ms=0`` with no concurrency)
    degenerates to the PR-1 solo behavior; engine-backed steps otherwise
    route through the :class:`~mpi_tpu.serve.batch.MicroBatcher`.
    """

    def __init__(self, cache: Optional[EngineCache] = None, *,
                 batching: bool = True, batch_window_ms: float = 2.0,
                 batch_max: int = 8,
                 async_enabled: bool = True,
                 async_queue_max: int = 1024,
                 ticket_ttl_s: float = 600.0,
                 state_dir: Optional[str] = None,
                 checkpoint_every: int = 64,
                 state_degrade: str = "continue",
                 state_journal: bool = True,
                 journal_max_bytes: int = 1 << 20,
                 journal_max_age_s: float = 300.0,
                 state_keep: int = 2,
                 request_timeout_s: Optional[float] = None,
                 step_retries: int = 2,
                 retry_backoff_s: float = 0.05,
                 degrade: bool = True,
                 faults=None,
                 obs=None,
                 tune_cache=None,
                 defer_restore: bool = False):
        self.obs = obs                  # mpi_tpu.obs.Obs or None (off)
        # autotuned-plan application is OPT-IN: a TuneCache (or a path to
        # one) makes every tpu create consult the cache on compile miss;
        # None (the default) leaves the build path byte-identical
        if isinstance(tune_cache, str):
            from mpi_tpu.tune import TuneCache

            tune_cache = TuneCache(tune_cache)
        self.tune_cache = tune_cache
        self.cache = cache if cache is not None else EngineCache()
        self.batcher = (
            MicroBatcher(window_ms=batch_window_ms, max_batch=batch_max)
            if batching else None
        )
        # the async ticket path (opt-in per request; --no-async removes
        # it entirely).  The dispatch-loop thread starts lazily on the
        # first enqueue, so a sync-only workload never runs it.
        self.dispatcher = (
            AsyncDispatcher(self, window_s=max(0.0, batch_window_ms) / 1e3,
                            queue_max=async_queue_max,
                            ticket_ttl_s=ticket_ttl_s)
            if async_enabled else None
        )
        self._sessions: Dict[str, Session] = {}
        self._lock = threading.Lock()
        self._next = 0
        # cluster membership (mpi_tpu/cluster): None means single-process
        # mode — every cluster seam below is a no-op and the behavior is
        # bit-identical to the pre-cluster stack
        self.cluster = None
        # admission control (mpi_tpu/admission): armed by
        # AdmissionControl.arm(); None (the default) keeps every
        # admission seam a no-op and the stack bit-identical to pre-16
        self.admission = None
        # step listeners (the aio front's stream hub): called after every
        # committed step/board-write, often with the session lock held —
        # a listener must only flip flags and wake a poller, never block
        self._step_listeners: list = []
        self._listeners_lock = threading.Lock()
        # fault tolerance
        self.request_timeout_s = _normalize_timeout(request_timeout_s)
        if step_retries < 0:
            raise ValueError(f"step_retries must be >= 0, got {step_retries}")
        self.step_retries = int(step_retries)
        self.retry_backoff_s = max(0.0, float(retry_backoff_s))
        self.degrade = bool(degrade)
        if isinstance(faults, str):
            from mpi_tpu.serve.faults import FaultInjector

            faults = FaultInjector.from_spec(faults)
        self.faults = faults
        # --state-degrade policy: what to do with session verbs while
        # persistence is degraded.  "continue" (default) keeps serving
        # and re-checkpoints when the disk heals; "readonly" refuses
        # mutating verbs (503 + Retry-After); "shed" refuses all
        # session verbs so a balancer drains this node
        if state_degrade not in ("continue", "readonly", "shed"):
            raise ValueError(
                f"state_degrade must be continue|readonly|shed, "
                f"got {state_degrade!r}")
        self.state_degrade = state_degrade
        self.store = (recovery.StateStore(
            state_dir, checkpoint_every,
            journal=state_journal,
            journal_max_bytes=journal_max_bytes,
            journal_max_age_s=journal_max_age_s,
            keep=state_keep)
            if state_dir else None)
        if self.store is not None:
            self.store.obs = obs
            if self.faults is not None:
                # the io fault sites fire inside StateStore._io — the
                # one choke point every persisted byte flows through
                self.store.fault_hook = self.faults.io_hook
        self.engine_failures = 0
        self.watchdog_timeouts = 0
        self.degraded_total = 0
        self.restored_sessions = 0
        self.restore_errors = 0
        self.store_errors = 0
        self._last_dispatch_ok: Optional[float] = None
        if self.obs is not None:
            self.obs.bind_manager(self)
        # defer_restore: cluster mode shares --state-dir across nodes, so
        # boot must NOT slurp every record — attach_cluster restores only
        # the sessions this node owns under the current ring
        if self.store is not None and not defer_restore:
            self._restore_all()

    # -- lifecycle ---------------------------------------------------------

    def attach_cluster(self, node) -> None:
        """Join a cluster (``mpi_tpu/cluster``): session ids and ticket
        ids gain the node's tag so any front can route them, and
        ``usage()``/``health()`` grow their ``cluster`` roll-up blocks.
        Called once at serve startup, before traffic."""
        self.cluster = node
        if self.dispatcher is not None:
            self.dispatcher.id_suffix = f"@{node.tag}"
        if self.admission is not None:
            # quotas become cluster-wide: admit against gossiped peer
            # window spend, not this node's slice
            self.admission.attach_cluster(node)
        if self.store is not None:
            self._restore_owned(node)
            node.sync_local_sessions()

    def _restore_owned(self, node) -> None:
        """The cluster half of boot restore (the state dir is shared):
        restore only the records this node owns — its own tag's sids
        plus anything the ring or a learned route places here.  Runs
        before traffic, so placement cannot move mid-restore."""
        held = set(self.session_ids())
        for rec in self.store.load_records():
            sid = rec["id"]
            if sid in held or node.owner_addr(sid) != node.id:
                continue
            try:
                self._restore_one(rec)
            except Exception as e:  # noqa: BLE001 — salvage the rest
                self.restore_errors += 1
                print(f"note: could not restore session {sid!r}: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
        if self.restored_sessions:
            print(f"[mpi_tpu] restored {self.restored_sessions} session(s) "
                  f"from {self.store.state_dir}", file=sys.stderr)

    def adopt_session(self, sid: str) -> bool:
        """Failover/drain adoption: restore one session from the shared
        state dir via the deterministic replay path.  True when the
        session is (now) live here; False when there is nothing to adopt
        (no record — the session was closed, or its checkpoint was lost
        with the dead node's local disk) or the replay failed."""
        with self._lock:
            if sid in self._sessions:
                return True             # already here (re-delivered adopt)
        if self.store is None:
            return False
        rec = self.store.load_record(sid)
        if rec is None:
            return False
        try:
            t0 = time.perf_counter()
            self._restore_one(rec)
            if self.obs is not None:
                self.obs.event("session_adopt",
                               time.perf_counter() - t0, t0, sid=sid,
                               generation=int(rec["generation"]))
        except Exception as e:  # noqa: BLE001 — count, report un-adopted
            self.restore_errors += 1
            print(f"note: could not adopt session {sid!r}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return False
        return True

    def checkpoint_now(self, sid: str) -> None:
        """Force a full-snapshot checkpoint at the session's CURRENT
        generation (the drain path: the adopter must replay zero
        generations).  Raises ``KeyError`` for unknown sids."""
        session = self.get(sid)
        if self.store is None:
            return
        with session.lock:
            if session.engine is not None:
                if self._sharded(session.engine):
                    # per-shard drain checkpoint: each device shard is
                    # fetched and packed independently — no full-board
                    # host array even at handoff time
                    tiles = session.engine.shard_snapshots(session.grid)
                    self._persist(session, shards=tiles, raise_errors=True)
                    return
                grid_np = session.engine.fetch(session.grid)
            else:
                grid_np = np.asarray(session.grid, dtype=np.uint8)
            # a drain/recovery checkpoint MUST land or visibly fail —
            # the caller decides whether to hand the session off
            self._persist(session, grid_np, raise_errors=True)

    def release(self, sid: str) -> None:
        """Drop a session locally WITHOUT deleting its durable record —
        the drain handoff: the successor restores from that record, so
        close()'s delete would lose the session.  Raises ``KeyError``
        for unknown sids."""
        with self._lock:
            session = self._sessions.pop(sid, None)
        if session is None:
            raise KeyError(sid)
        with session.lock:
            session.closed = True
            session.grid = None
            session.engine = None
        if self.admission is not None:
            self.admission.gate.drop_session(sid)
        if self.store is not None:
            # drop in-memory journal state only — the durable chain is
            # the successor's restore source
            self.store.forget(sid)

    def persistence_retry(self) -> None:
        """Flush the degraded-store backlog when the retry backoff has
        elapsed (called from lock-free seams: the top of ``step`` and
        ``health``).  Each pending session gets a fresh full-snapshot
        checkpoint — the write that failed may have been a journal
        entry whose in-memory diff base is long gone.  The first write
        is the probe; if the disk is still sick the store re-arms its
        backoff and this returns quietly."""
        store = self.store
        if store is None or not store.retry_ready():
            return
        try:
            store.retry_deletes()
        except OSError:
            return
        for sid in store.take_pending():
            try:
                self.checkpoint_now(sid)
            except KeyError:
                store.discard_pending(sid)  # released/closed meanwhile
            except OSError:
                return                  # still sick; backoff re-armed

    def _storage_gate(self, mutating: bool = True) -> None:
        """Enforce ``--state-degrade`` while persistence is degraded:
        ``readonly`` refuses mutating session verbs, ``shed`` refuses
        all of them (``continue``, the default, refuses nothing).  The
        transport maps the raise to a structured 503 with Retry-After
        sized by the store's backoff."""
        store = self.store
        if store is None or self.state_degrade == "continue":
            return
        if not store.is_degraded():
            return
        if self.state_degrade == "shed" or mutating:
            wait = max(store.retry_in_s(), 0.5)
            raise recovery.StorageDegradedError(
                f"persistence degraded and --state-degrade is "
                f"{self.state_degrade}; retry in {wait:.1f}s", wait)

    def session_ids(self) -> list:
        with self._lock:
            return list(self._sessions)

    def create(self, spec: dict, timeout_s: Optional[float] = None,
               sid: Optional[str] = None,
               tenant: Optional[str] = None) -> dict:
        """Create a board.  ``timeout_s`` (explicit only — the default
        budget deliberately does NOT cover create: a cold create
        legitimately spends many seconds in XLA, and an abandoned create
        worker would still register its session) bounds the build.
        ``sid`` forces the session id (cluster mode: the front that took
        the request allocates the id so ring placement and id agree);
        None keeps the local ``s<n>`` allocation.  ``tenant`` (armed
        admission only) owns the session: its concurrency cap gates the
        create, and every step settles against its quota window."""
        deadline = _Deadline(_normalize_timeout(timeout_s))
        return _watchdog_call(lambda: self._create(spec, sid=sid,
                                                   tenant=tenant),
                              deadline, "create")

    def _create(self, spec: dict, sid: Optional[str] = None,
                tenant: Optional[str] = None) -> dict:
        self._storage_gate(mutating=True)
        config, segments = _parse_spec(spec)
        adm = self.admission
        if adm is not None:
            # cap check BEFORE the build — a rejected tenant must not
            # spend compile time (enforcement precedes device work)
            tenant = tenant if tenant is not None else adm.resolve(None)
            adm.admit_session(tenant)
        t0 = time.perf_counter()
        with _span(self.obs, "create", backend=config.backend,
                   rows=config.rows, cols=config.cols):
            if config.backend == "tpu":
                session = self._create_tpu(config, segments)
            else:
                session = self._create_host(config)
        session.setup_s = time.perf_counter() - t0
        session.spec = dict(spec)
        with self._lock:
            if sid is None:
                self._next += 1
                sid = f"s{self._next}"
            elif sid in self._sessions:
                raise ConfigError(f"session id {sid!r} already exists")
            session.id = sid
            self._sessions[sid] = session
        if adm is not None:
            session.tenant = tenant
            session.qos = adm.registry.get(tenant)["default_class"]
            adm.gate.note_session(sid, tenant)
        self._persist(session)
        info = self.describe(session)
        info["cache"] = self.cache.stats()
        return info

    def _create_tpu(self, config: GolConfig, segments,
                    initial=None) -> Session:
        from mpi_tpu.backends.tpu import build_engine, device_count
        from mpi_tpu.parallel.mesh import choose_mesh_shape, make_mesh

        mesh_shape = config.mesh_shape or choose_mesh_shape(device_count())
        sig = plan_signature(config, mesh_shape, segments)
        if not self.cache.breaker_allows(sig):
            # quarantined plan: never hand a fresh board to a sick engine
            if not self.degrade:
                raise EngineUnavailableError(
                    "engine circuit breaker open for this plan signature "
                    "and degradation is disabled")
            session = self._degraded_host_session(config, initial=initial)
            session.plan_sig = sig
            return session
        # the tune cache rides the existing compile-miss seam: the
        # signature is the REQUESTED plan's, so a cached winner costs
        # zero extra recompiles — hit sessions share the tuned engine
        engine, hit = self.cache.get_or_build(
            sig, lambda: build_engine(config, mesh=make_mesh(mesh_shape),
                                      tune=self.tune_cache))
        if self.faults is not None:
            # idempotent: cached engines get the same hook re-installed
            engine.fault_hook = self.faults.engine_hook
        # same idempotent-install idiom: a cached engine follows THIS
        # manager's obs setting (None detaches a previous manager's)
        engine.obs = self.obs
        # the compact plan tag keys the engine's cost cards and the usage
        # ledger's per-signature series (bounded cardinality: signatures,
        # never sessions)
        engine.sig_label = signature_label(sig)
        grid = engine.init_grid(initial=initial, seed=config.seed)
        # precompile the requested segment set (a no-op on a cache hit —
        # the signature pins the set, so the hit engine already has it)
        engine.compile_segments(grid, segments)
        return Session("?", config, engine=engine, grid=grid, cache_hit=hit,
                       plan_sig=sig)

    def _create_host(self, config: GolConfig) -> Session:
        rule, boundary = config.rule, config.boundary
        if config.backend == "serial":
            def stepper(g, n):
                return evolve_np(g, n, rule, boundary)
        elif config.backend == "cpp":
            from mpi_tpu.backends.cpp import evolve_cpp, load_library

            load_library()              # build/dlopen is setup, like compile

            def stepper(g, n):
                return evolve_cpp(g, n, rule, boundary)
        else:  # cpp-par
            from mpi_tpu.backends.cpp import (
                evolve_par_cpp, load_library, plan_tiles,
            )

            load_library()
            tiles = plan_tiles((config.rows, config.cols), config.workers,
                               rule.radius)

            def stepper(g, n):
                return evolve_par_cpp(g, n, rule, boundary, tiles=tiles)

        grid = init_tile_np(config.rows, config.cols, config.seed)
        return Session("?", config, stepper=stepper, grid=grid)

    def _degraded_host_session(self, config: GolConfig, initial=None,
                               reason: str = "circuit breaker open at create",
                               ) -> Session:
        """A session born degraded: the oracle stepper over a numpy grid
        (bit-identical to the engine it stands in for)."""
        rule, boundary = config.rule, config.boundary

        def stepper(g, n):
            return evolve_np(g, n, rule, boundary)

        if callable(initial):
            # a shard-form restore hands a region loader; the host
            # oracle needs the assembled board
            initial = initial(0, config.rows, 0, config.cols)
        grid = (np.asarray(initial, dtype=np.uint8) if initial is not None
                else init_tile_np(config.rows, config.cols, config.seed))
        session = Session("?", config, stepper=stepper, grid=grid)
        session.degraded = True
        session.degraded_reason = reason
        self.degraded_total += 1
        return session

    def close(self, sid: str, timeout_s: Optional[float] = None) -> dict:
        deadline = _Deadline(self._budget(timeout_s))
        return _watchdog_call(lambda: self._close(sid), deadline,
                              f"close({sid})")

    def _close(self, sid: str) -> dict:
        with self._lock:
            session = self._sessions.pop(sid, None)
        if session is None:
            raise KeyError(sid)
        with session.lock:
            session.closed = True
            session.grid = None         # free device/host buffers now; the
            session.engine = None       # cached engine survives for reuse
        if self.admission is not None:
            self.admission.gate.drop_session(sid)
        if self.store is not None:
            self.store.delete(sid)
        return {"id": sid, "closed": True}

    def get(self, sid: str) -> Session:
        with self._lock:
            session = self._sessions.get(sid)
        if session is None:
            raise KeyError(sid)
        return session

    # -- step listeners ----------------------------------------------------

    def add_step_listener(self, fn) -> None:
        """Register ``fn(session)`` to run after every committed step or
        board write (all commit paths: solo, microbatch, async ticket).
        Called with the session lock frequently held — the callback must
        be non-blocking (set a flag, wake a selector)."""
        with self._listeners_lock:
            self._step_listeners.append(fn)

    def remove_step_listener(self, fn) -> None:
        with self._listeners_lock:
            try:
                self._step_listeners.remove(fn)
            except ValueError:
                pass

    def _notify_step(self, session: Session) -> None:
        with self._listeners_lock:
            listeners = tuple(self._step_listeners)
        for fn in listeners:
            try:
                fn(session)
            except Exception:  # noqa: BLE001 — a viewer must not fail a step
                pass

    # -- checkpoint / restore ---------------------------------------------

    @staticmethod
    def _sharded(engine) -> bool:
        """True when the engine spans more than one device shard — the
        cue to checkpoint shard-by-shard instead of through one
        full-board host array (sparse engines are always 1x1, so the
        shard path never sees a SparseState)."""
        return engine is not None and engine.mi * engine.mj > 1

    def _persist(self, session: Session, grid_np=None,  # lint: disable=lock-discipline -- caller holds session.lock (step path) or the session is pre-publication (create/restore)
                 raise_errors: bool = False, shards=None) -> None:
        """Write the session's full durable record (caller holds the
        session lock on the step path; create/restore call it
        pre-publication).  ``grid_np``: a freshly fetched host grid to
        snapshot; ``shards``: ``[(r0, c0, tile), ...]`` device-shard
        tiles to snapshot in shard form (never assembled); None for both
        keeps the previous snapshot.  Store failures are counted, noted,
        and swallowed — durability must degrade, not take the step down
        with it — unless ``raise_errors`` (the drain path: handing off a
        session whose checkpoint did not land would lose generations)."""
        if self.store is None or session.spec is None:
            return
        try:
            t0 = time.perf_counter()
            if shards is not None:
                snap = recovery.encode_grid_shards(
                    shards, session.config.rows, session.config.cols)
                snap["generation"] = session.generation
                session.ckpt = snap
            elif grid_np is not None:
                snap = recovery.encode_grid(grid_np)
                snap["generation"] = session.generation
                session.ckpt = snap
            self.store.save(session.id, session.spec, session.generation,
                            session.ckpt)
            if self.obs is not None:
                dt = time.perf_counter() - t0
                self.obs.checkpoint_write.observe(dt)
                self.obs.event("checkpoint_write", dt, t0, sid=session.id,
                               generation=session.generation,
                               snapshot=(grid_np is not None
                                         or shards is not None))
        except recovery.StorageDegradedError:
            # fast-fail while degraded: already queued as pending and
            # counted by the store; no stderr spam per skipped write
            if raise_errors:
                raise
        except Exception as e:  # noqa: BLE001 — durability is best-effort
            self.store_errors += 1
            print(f"note: state-dir write failed for {session.id}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            if raise_errors:
                raise

    def _checkpoint(self, session: Session) -> None:  # lint: disable=lock-discipline -- caller holds session.lock (documented contract)
        """Persist a committed step (caller holds ``session.lock``).
        The generation lands every step — as an appended journal entry
        when journaling (a content delta when the grid rode along, a
        bare mark otherwise; the store compacts to a full record on its
        size/age triggers), as a full record rewrite otherwise.  The
        grid is fetched only every ``checkpoint_every`` generations
        (fetching the device grid is a sync)."""
        if self.store is None or session.spec is None:
            return
        grid_np = None
        tiles = None
        last = session.ckpt["generation"] if session.ckpt else 0
        if session.generation - last >= self.store.checkpoint_every:
            try:
                if session.engine is not None:
                    if self._sharded(session.engine):
                        # shard-form fetch: one host tile per device
                        # shard, packed independently downstream — the
                        # journal then appends only the CHANGED shards
                        tiles = session.engine.shard_snapshots(
                            session.grid)
                    else:
                        grid_np = session.engine.fetch(session.grid)
                else:
                    grid_np = np.asarray(session.grid, dtype=np.uint8)
            except Exception as e:  # noqa: BLE001 — snapshot is an optimization
                self.store_errors += 1
                print(f"note: checkpoint fetch failed for {session.id}: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
                grid_np = None
                tiles = None
        try:
            t0 = time.perf_counter()
            if tiles is not None:
                snap = recovery.encode_grid_shards(
                    tiles, session.config.rows, session.config.cols)
                snap["generation"] = session.generation
                session.ckpt = snap
            elif grid_np is not None:
                snap = recovery.encode_grid(grid_np)
                snap["generation"] = session.generation
                session.ckpt = snap
            info = self.store.commit_step(
                session.id, session.spec, session.generation, session.ckpt,
                grid=grid_np,
                shards=None if tiles is None else
                (session.config.rows, session.config.cols, tiles))
            if self.obs is not None:
                dt = time.perf_counter() - t0
                if info["form"] == "journal":
                    self.obs.event("journal_append", dt, t0,
                                   sid=session.id,
                                   generation=session.generation,
                                   kind=info["kind"],
                                   bytes=info["bytes"])
                else:
                    self.obs.checkpoint_write.observe(dt)
                    self.obs.event("checkpoint_write", dt, t0,
                                   sid=session.id,
                                   generation=session.generation,
                                   snapshot=grid_np is not None)
        except recovery.StorageDegradedError:
            pass                        # queued as pending; retried later
        except Exception as e:  # noqa: BLE001 — durability is best-effort
            self.store_errors += 1
            print(f"note: state-dir write failed for {session.id}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)

    def _restore_all(self) -> None:
        for rec in self.store.load_records():
            try:
                self._restore_one(rec)
            except Exception as e:  # noqa: BLE001 — salvage the rest
                self.restore_errors += 1
                print(f"note: could not restore session "
                      f"{rec.get('id')!r}: {type(e).__name__}: {e}",
                      file=sys.stderr)
        if self.restored_sessions:
            print(f"[mpi_tpu] restored {self.restored_sessions} session(s) "
                  f"from {self.store.state_dir}", file=sys.stderr)

    def _restore_one(self, rec: dict) -> None:  # lint: disable=lock-discipline -- pre-publication: the session is not in the table yet, no other thread can reach it
        config, segments = _parse_spec(rec["spec"])
        target_gen = int(rec["generation"])
        snap = rec.get("snapshot")
        start_gen = int(snap["generation"]) if snap else 0
        if not 0 <= start_gen <= target_gen:
            raise ValueError(
                f"snapshot generation {start_gen} outside 0..{target_gen}")
        t0 = time.perf_counter()
        if config.backend == "tpu":
            # restore through a region loader: each device shard pulls
            # only its own rectangle, decoding only the stored shards
            # that intersect it — the full board never materializes on
            # this host (legacy full-grid snapshots decode once, lazily,
            # behind the same interface)
            initial = recovery.snapshot_loader(snap) if snap else None
            session = self._create_tpu(config, segments, initial=initial)
        else:
            initial = recovery.decode_grid(snap) if snap else None
            session = self._create_host(config)
            if initial is not None:
                session.grid = initial
        session.generation = start_gen
        # deterministic replay to the recorded generation: stepping is a
        # pure function of (grid, n) and every backend is bit-identical
        # to the oracle (PARITY.md), so the restored board equals an
        # uninterrupted run.  Engine replay goes in depth-1 chunks — the
        # one depth every session precompiles — so restore costs
        # dispatches, never fresh XLA programs.
        n = target_gen - start_gen
        if n > 0:
            if session.engine is not None:
                import jax

                session.engine.ensure_compiled(session.grid, 1)
                for _ in range(n):
                    session.grid = session.engine.step(session.grid, 1)
                jax.block_until_ready(session.grid)
            else:
                session.grid = session.stepper(session.grid, n)
            session.generation = target_gen
        session.setup_s = time.perf_counter() - t0
        if self.obs is not None:
            self.obs.restore_replay.observe(session.setup_s)
            self.obs.event("restore_replay", session.setup_s, t0,
                           sid=rec["id"], replayed=n,
                           backend=config.backend)
        session.spec = dict(rec["spec"])
        session.ckpt = snap
        session.restored = True
        sid = rec["id"]
        with self._lock:
            session.id = sid
            self._sessions[sid] = session
            self._next = max(self._next, recovery._sid_ordinal(sid))
        if self.admission is not None:
            # records don't carry tenancy; restored boards settle to the
            # default tenant rather than escaping the books entirely
            session.tenant = self.admission.resolve(None)
            session.qos = self.admission.registry.get(
                session.tenant)["default_class"]
            self.admission.gate.note_session(sid, session.tenant)
        self.restored_sessions += 1
        self._persist(session)

    # -- fault handling ----------------------------------------------------

    def _budget(self, timeout_s: Optional[float]) -> Optional[float]:
        if timeout_s is not None:
            return _normalize_timeout(timeout_s)
        return self.request_timeout_s

    def _engine_failure(self, session: Session, sig, err,
                        timeout: bool = False) -> bool:
        """Count one engine failure; returns True when the signature's
        breaker is now open (caller should degrade, not retry)."""
        self.engine_failures += 1
        if timeout:
            self.watchdog_timeouts += 1
        session.last_error = f"{type(err).__name__}: {err}"
        if self.obs is not None:
            self.obs.engine_failures.inc()
            self.obs.event("engine_failure", sid=session.id,
                           error=session.last_error, timeout=timeout)
        opened = self.cache.record_failure(sig)
        if opened:
            print(f"note: circuit breaker OPEN for plan of session "
                  f"{session.id} after consecutive engine failures "
                  f"(last: {session.last_error})", file=sys.stderr)
        return opened

    def _degrade_session(self, session: Session, reason: str) -> None:  # lint: disable=lock-discipline -- deliberately lock-free: the trigger is a wedged dispatch still holding session.lock; see docstring
        """Swap ``session`` for a serial_np replacement rebuilt by
        deterministic replay at the last *committed* generation.

        Deliberately does NOT take ``session.lock``: the usual trigger is
        a wedged dispatch still holding it.  The replacement is built
        from the durable facts (spec/seed/checkpoint + committed
        generation — plain attribute reads, atomic under the GIL), the
        table entry is swapped under the manager lock, and the old object
        is orphaned: a late-completing worker commits into the orphan,
        which no request can reach anymore."""
        with self._lock:
            if self._sessions.get(session.id) is not session:
                return                  # someone else already swapped it
        grid = self._replay_np(session.config, session.generation,
                               session.ckpt)
        repl = self._degraded_host_session(session.config, initial=grid,
                                           reason=reason)
        repl.generation = session.generation
        repl.plan_sig = session.plan_sig
        repl.spec = session.spec
        repl.ckpt = session.ckpt
        repl.restored = session.restored
        repl.cache_hit = session.cache_hit
        repl.setup_s = session.setup_s
        repl.steady_s = session.steady_s
        repl.batched_steps = session.batched_steps
        repl.last_error = session.last_error
        with self._lock:
            if self._sessions.get(session.id) is not session:
                return
            repl.id = session.id
            self._sessions[session.id] = repl
        session.closed = True           # orphan: late workers see closed
        if self.obs is not None:
            self.obs.event("degrade", sid=repl.id, reason=reason)
        print(f"note: session {repl.id} degraded to the serial_np oracle "
              f"({reason}); results stay bit-identical, throughput drops",
              file=sys.stderr)
        self._persist(repl)

    @staticmethod
    def _replay_np(config: GolConfig, generation: int,
                   ckpt: Optional[dict]) -> np.ndarray:
        """The board at ``generation``, rebuilt on the host oracle from
        the last checkpoint (or the seed).  Never touches the device —
        a failing engine may have corrupted or donated its buffers."""
        if ckpt is not None:
            grid = recovery.decode_grid(ckpt)
            start = int(ckpt["generation"])
        else:
            grid = init_tile_np(config.rows, config.cols, config.seed)
            start = 0
        return evolve_np(grid, generation - start, config.rule,
                         config.boundary)

    def _mark_dispatch_ok(self) -> None:
        self._last_dispatch_ok = time.monotonic()

    def last_dispatch_age_s(self) -> Optional[float]:
        """Seconds since the last committed dispatch, None before the
        first — the freshness SLO's input (and /healthz's age field)."""
        if self._last_dispatch_ok is None:
            return None
        return time.monotonic() - self._last_dispatch_ok

    # -- verbs -------------------------------------------------------------

    def step(self, sid: str, steps: int = 1,
             timeout_s: Optional[float] = None, *,
             _deadline: Optional[_Deadline] = None,
             _use_batcher: bool = True, _unit: bool = False) -> dict:
        """Blocking step.  The underscored keywords are the async
        dispatcher's hooks into this same retry/breaker/watchdog loop:
        ``_deadline`` carries a ticket's enqueue-time budget,
        ``_use_batcher=False`` skips the sync coalescing queue (the one
        dispatch-loop thread can never coalesce with itself), and
        ``_unit=True`` chains depth-1 dispatches instead of compiling a
        new depth.  The sync path never sets any of them."""
        if steps < 1:
            raise ConfigError(f"steps must be >= 1, got {steps}")
        self.persistence_retry()
        self._storage_gate(mutating=True)
        deadline = (_deadline if _deadline is not None
                    else _Deadline(self._budget(timeout_s)))
        attempt = 0
        while True:
            session = self.get(sid)
            sig = session.plan_sig if session.engine is not None else None
            if sig is not None and not self.cache.breaker_allows(sig):
                if not self.degrade:
                    raise EngineUnavailableError(
                        f"engine circuit breaker open for session {sid} "
                        f"and degradation is disabled")
                self._degrade_session(session, "circuit breaker open")
                continue                # re-get: now a host-path session
            try:
                result = _watchdog_call(
                    lambda: self._step_entry(session, steps,
                                             use_batcher=_use_batcher,
                                             unit=_unit),
                    deadline, f"step({sid})")
            except (KeyError, ConfigError):
                raise
            except DeadlineError as e:
                if sig is not None:
                    self._engine_failure(session, sig, e, timeout=True)
                raise                   # the budget is gone — no retry
            except Exception as e:  # noqa: BLE001 — engine failures only
                if sig is None:
                    raise               # host failures are not retriable
                opened = self._engine_failure(session, sig, e)
                attempt += 1
                if opened:
                    continue            # loop top degrades (or 503s)
                rem = deadline.remaining()
                if attempt > self.step_retries or (rem is not None and rem <= 0):
                    raise EngineStepError(
                        f"engine step failed after {attempt} attempt(s): "
                        f"{type(e).__name__}: {e}") from e
                pause = self.retry_backoff_s * (2 ** (attempt - 1))
                if rem is not None:
                    pause = min(pause, rem)
                if pause > 0:
                    time.sleep(pause)
                continue
            if sig is not None:
                self.cache.record_success(sig)
            return result

    def _step_entry(self, session: Session, steps: int,
                    use_batcher: bool = True, unit: bool = False) -> dict:
        """One step attempt: the batched path when eligible, else solo
        under the session lock.  Runs inside the watchdog worker when a
        budget is set."""
        if use_batcher and self.batcher is not None \
                and session.engine is not None \
                and session.plan_sig is not None:
            # engine-backed steps coalesce: concurrent same-signature
            # same-depth requests share ONE stacked device dispatch; the
            # batcher takes session.lock (leader-side) and falls back to
            # _step_locked when alone or on any batched-path failure
            return self.batcher.submit(self, session, steps)
        obs = self.obs
        if obs is not None:
            t0 = time.perf_counter()
            session.lock.acquire()
            wait = time.perf_counter() - t0
            obs.lock_wait_series.observe(wait)
            if wait >= 1e-3:
                # only a *contended* wait is a trace-worthy fact; the
                # uncontended acquire would just be ring noise
                obs.event("lock_wait", wait, t0, sid=session.id)
        else:
            session.lock.acquire()
        try:
            if session.closed:
                raise KeyError(session.id)
            return self._step_locked(session, steps, unit=unit)
        finally:
            session.lock.release()

    def _step_locked(self, session: Session, steps: int,  # lint: disable=lock-discipline -- caller (_step_entry) holds session.lock for the whole call
                     unit: bool = False) -> dict:
        """The solo step body; caller holds ``session.lock`` (the step
        path via :meth:`_step_entry`, the microbatch leader for
        lone/fallback entries, the async dispatcher's solo fallback —
        the latter with ``unit=True``: chain depth-1 dispatches instead
        of compiling depth ``steps``)."""
        obs = self.obs
        if session.engine is not None:
            import jax

            # a depth never seen before compiles here — that is setup,
            # not stepping; charge it to setup_s so throughput numbers
            # stay honest (same accounting as run_tpu's phases).  The
            # engine itself records the compile event on a real miss, so
            # the hot path adds no span around the dict hit.  The unit
            # path only ever needs depth 1 — the depth every session
            # precompiles — so it never pays a fresh XLA program.
            t0 = time.perf_counter()
            session.engine.ensure_compiled(session.grid, 1 if unit else steps)
            t1 = time.perf_counter()
            session.setup_s += t1 - t0
            # step donates the input buffer: replace the reference
            if unit:
                grid = session.engine.step_units(session.grid, steps)
            else:
                grid = session.engine.step(session.grid, steps)
            td = time.perf_counter() if obs is not None else 0.0
            jax.block_until_ready(grid)
            session.grid = grid
            t2 = time.perf_counter()
            session.steady_s += t2 - t1
            if obs is not None:
                # ONE event for the dispatch+sync pair (block_s splits
                # them at read time) through the pre-bound series — the
                # whole per-step cost of observability is ~3 µs
                if unit:
                    obs.event("device_dispatch", t2 - t1, t1,
                              sid=session.id, steps=steps, unit=True,
                              block_s=round(t2 - td, 9))
                else:
                    obs.event("device_dispatch", t2 - t1, t1,
                              sid=session.id, steps=steps,
                              block_s=round(t2 - td, 9))
                if getattr(session.engine, "tuned_plan", None):
                    obs.dispatch_solo_tuned.observe(t2 - t1)
                else:
                    obs.dispatch_solo.observe(t2 - t1)
                tel = obs.telemetry
                if tel is not None:
                    tel.dispatch_digest.observe(t2 - t1)
                # usage ledger: one committed sync.  The unit path is an
                # async solo chain (ONE block for `steps` depth-1
                # executions); its FLOPs are the depth-1 card times the
                # chain length.  A batched-path failure re-enters here,
                # so fallbacks are counted exactly once — by this site.
                card = session.engine.cost_card(1 if unit else steps)
                flops = 0.0 if card is None else (
                    card.flops * steps if unit else card.flops)
                obs.ledger.record(
                    "unit" if unit else "solo", session.engine.sig_label,
                    t2 - t1,
                    [(session.id, steps, steps * session.config.cells,
                      flops)])
                sa = None
                if session.engine.sparse_plan is not None:
                    # activity readout AFTER the sync (tiny tile-map
                    # reduce + fetch) — the span every sparse dispatch
                    # leaves in the trace
                    sa = session.engine.sparse_stats(session.grid)
                    obs.event("sparse_step", 0.0, t2, sid=session.id,
                              active_tiles=sa["active_tiles"],
                              active_fraction=round(
                                  sa["active_fraction"], 6),
                              mode=sa["mode"])
                fl = obs.flight
                if fl is not None:
                    fl.record("unit" if unit else "solo",
                              engine=session.engine, steps=steps,
                              session=session.id, setup_s=t1 - t0,
                              device_s=t2 - t1, block_s=t2 - td,
                              sparse=sa)
            self._mark_dispatch_ok()
        else:
            t0 = time.perf_counter()
            session.grid = session.stepper(session.grid, steps)
            t1 = time.perf_counter()
            session.steady_s += t1 - t0
            if obs is not None:
                obs.event("host_step", t1 - t0, t0,
                          sid=session.id, steps=steps)
                obs.dispatch_host.observe(t1 - t0)
                tel = obs.telemetry
                if tel is not None:
                    tel.dispatch_digest.observe(t1 - t0)
                # host wall is metered apart from device-seconds (the
                # ledger's host_s bucket); degraded tpu sessions keep
                # their signature row, plain host backends get "-"
                obs.ledger.record(
                    "host",
                    signature_label(session.plan_sig)
                    if session.plan_sig is not None else None,
                    t1 - t0,
                    [(session.id, steps, steps * session.config.cells,
                      0.0)])
                fl = obs.flight
                if fl is not None:
                    fl.record("host", steps=steps, session=session.id,
                              device_s=t1 - t0)
        session.generation += steps
        self._checkpoint(session)
        self._notify_step(session)
        return {"id": session.id, "generation": session.generation,
                "steps": steps}

    # -- admission (ISSUE 16) ----------------------------------------------

    def admission_check(self, sid: str, steps: int,
                        tenant: Optional[str] = None,
                        qos: Optional[str] = None) -> Optional[str]:
        """Gate one step request BEFORE any device work: resolve the
        request's class (tenant default, header override capped at the
        tenant ceiling), run the shed ladder, and charge the CostCard
        estimate against the tenant's remaining window quota.  Returns
        the resolved class (None when admission is unarmed — the
        transport then behaves exactly as pre-16).  Raises
        :class:`~mpi_tpu.admission.AdmissionReject` (429), or
        ``ConfigError`` when the header names a tenant that is not the
        session's owner (accounting must stay honest)."""
        adm = self.admission
        if adm is None:
            return None
        session = self.get(sid)         # unknown session -> 404 first
        owner = session.tenant if session.tenant is not None \
            else adm.resolve(None)
        if tenant:
            claimed = adm.resolve(tenant)
            if claimed != owner:
                raise ConfigError(
                    f"session {sid!r} belongs to tenant {owner!r}, "
                    f"not {claimed!r}")
        resolved = adm.resolve_class(owner, qos)
        est_device_s, est_cells = adm.estimate(session, steps)
        adm.admit_step(owner, resolved, est_device_s, est_cells)
        return resolved

    # -- async (ticketed) stepping ----------------------------------------

    def step_async(self, sid: str, steps: int = 1,
                   timeout_s: Optional[float] = None,
                   qos: Optional[str] = None) -> dict:
        """Enqueue a step and return immediately with a ticket.  The
        budget starts NOW, at enqueue — a ticket that expires while
        queued is drained with :class:`DeadlineError` without ever
        dispatching, and one that expires mid-flight stops advancing at
        the last committed unit round.  ``timeout_s`` follows the same
        convention as every blocking verb (explicit override beats the
        server default; <= 0 disables)."""
        if self.dispatcher is None:
            raise ConfigError("async stepping is disabled (--no-async)")
        if steps < 1:
            raise ConfigError(f"steps must be >= 1, got {steps}")
        self._storage_gate(mutating=True)   # reject at enqueue, not resolve
        session = self.get(sid)         # unknown session -> 404 at enqueue
        deadline = _Deadline(self._budget(timeout_s))
        t0 = time.perf_counter()
        adm = self.admission
        if adm is None:
            ticket = self.dispatcher.submit(sid, steps, deadline)
        else:
            # class + cost tags drive the dispatcher's weighted pick;
            # the admission decision itself already ran (transport) or
            # runs on the tenant default here (direct callers)
            resolved = qos if qos is not None else \
                adm.resolve_class(session.tenant if session.tenant
                                  is not None else adm.resolve(None), None)
            ticket = self.dispatcher.submit(
                sid, steps, deadline, qos=resolved,
                cost=adm.estimate_ops(session, steps))
        if self.obs is not None:
            self.obs.event("enqueue", time.perf_counter() - t0, t0,
                           sid=sid, ticket=ticket.id, steps=steps)
        return {"ticket": ticket.id, "id": sid, "status": "pending"}

    def ticket_result(self, tid: str, wait: bool = False,  # lint: disable=lock-discipline -- ticket status flips exactly once under _cv; a racy read settles via event.wait, terminal states are immutable
                      timeout_s: Optional[float] = None) -> dict:
        """A ticket's current outcome.  ``wait=True`` blocks until the
        ticket resolves (bounded by the usual request budget); a
        resolved-with-error ticket re-raises its stored exception, so
        the HTTP layer maps it to the SAME structured 503/404 the
        blocking path would have answered."""
        if self.dispatcher is None:
            raise KeyError(tid)
        ticket = self.dispatcher.get(tid)
        if wait:
            # the span records how long THIS read blocked — 0 when the
            # ticket had already resolved (emitted either way, so trace
            # tooling sees every waited read, not just the slow ones)
            t0 = time.perf_counter()
            if ticket.status == "pending":
                ticket.event.wait(self._budget(timeout_s))
            if self.obs is not None:
                dt = time.perf_counter() - t0
                self.obs.event("ticket_wait", dt, t0,
                               ticket=tid, sid=ticket.sid,
                               resolved=ticket.status != "pending")
                tel = self.obs.telemetry
                if tel is not None:
                    tel.ticket_wait_digest.observe(dt)
        if ticket.status == "error":
            raise ticket.error
        out = {"ticket": ticket.id, "id": ticket.sid,
               "status": ticket.status}
        if ticket.status == "done":
            out["result"] = ticket.result
        else:
            out["steps"] = ticket.steps
            out["remaining"] = ticket.remaining
        return out

    def snapshot(self, sid: str, timeout_s: Optional[float] = None) -> dict:
        self._storage_gate(mutating=False)
        deadline = _Deadline(self._budget(timeout_s))
        return _watchdog_call(lambda: self._snapshot(sid), deadline,
                              f"snapshot({sid})")

    def snapshot_array(self, sid: str, timeout_s: Optional[float] = None):
        """``(grid_np, generation, config)`` under the same lock/deadline
        discipline as :meth:`snapshot` — the transport layer's fetch for
        both wire formats (it formats JSON rows or a binary frame from
        the same array, so the two paths cannot disagree)."""
        deadline = _Deadline(self._budget(timeout_s))
        return _watchdog_call(lambda: self._snapshot_grid(sid), deadline,
                              f"snapshot({sid})")

    def _snapshot_grid(self, sid: str):
        session = self.get(sid)
        with session.lock:
            if session.closed:
                raise KeyError(sid)
            # generation must be captured with the grid, INSIDE the lock —
            # a concurrent step between fetch and return would otherwise
            # label this grid with a later generation (torn read)
            generation = session.generation
            if session.engine is not None:
                grid = session.engine.fetch(session.grid)
                if grid is None:
                    raise ConfigError(
                        "snapshot over HTTP needs single-host execution")
            else:
                grid = session.grid
        return np.asarray(grid, dtype=np.uint8), generation, session.config

    def _snapshot(self, sid: str) -> dict:
        grid, generation, config = self._snapshot_grid(sid)
        return {"id": sid, "generation": generation,
                "rows": config.rows, "cols": config.cols,
                "grid": format_grid_rows(grid)}

    @staticmethod
    def window_rects(x0: int, y0: int, h: int, w: int, rows: int,
                      cols: int, boundary: str):
        """Non-wrapping board rectangles covering a requested window,
        each tagged with its offset inside the output array:
        ``[(out_r, out_c, r0, c0, rh, rw), ...]``.  Periodic boards wrap
        (up to four rectangles); any other boundary requires the window
        to sit fully inside the board."""
        if h < 1 or w < 1:
            raise ConfigError(f"window extent must be >= 1, got {h}x{w}")
        if not (0 <= x0 < rows and 0 <= y0 < cols):
            raise ConfigError(
                f"window origin ({x0},{y0}) is off the {rows}x{cols} board")
        if h > rows or w > cols:
            raise ConfigError(
                f"window {h}x{w} exceeds the {rows}x{cols} board")
        wraps = x0 + h > rows or y0 + w > cols
        if wraps and boundary != "periodic":
            raise ConfigError(
                f"window [{x0}:{x0 + h}, {y0}:{y0 + w}] leaves the "
                f"{rows}x{cols} board and boundary {boundary!r} does "
                f"not wrap")
        r_spans = [(0, x0, min(h, rows - x0))]
        if x0 + h > rows:
            r_spans.append((rows - x0, 0, x0 + h - rows))
        c_spans = [(0, y0, min(w, cols - y0))]
        if y0 + w > cols:
            c_spans.append((cols - y0, 0, y0 + w - cols))
        return [(out_r, out_c, r0, c0, rh, rw)
                for out_r, r0, rh in r_spans
                for out_c, c0, rw in c_spans]

    def snapshot_window(self, sid: str, x0: int, y0: int, h: int, w: int,
                        timeout_s: Optional[float] = None):
        """``(window_np, generation, config)`` for one viewport — the
        O(viewport) read path: only device shards intersecting the
        window cross the host tunnel (per-shard ``device_get``), never a
        full-board gather.  A window crossing the periodic wrap is
        decomposed into up to four non-wrapping rectangles.  Same
        lock/deadline discipline as :meth:`snapshot_array`."""
        deadline = _Deadline(self._budget(timeout_s))
        return _watchdog_call(
            lambda: self._snapshot_window(sid, x0, y0, h, w), deadline,
            f"snapshot_window({sid})")

    def _snapshot_window(self, sid: str, x0: int, y0: int, h: int, w: int):
        session = self.get(sid)
        x0, y0, h, w = int(x0), int(y0), int(h), int(w)
        rects = self.window_rects(x0, y0, h, w, session.config.rows,
                                   session.config.cols,
                                   session.config.boundary)
        obs = self.obs
        timer = None
        fetched = {"n": 0, "s": 0.0}
        if obs is not None:
            series = obs.shard_fetch_series

            def timer(dt_s, _series=series, _f=fetched):
                _f["n"] += 1
                _f["s"] += dt_s
                _series.observe(dt_s)
        with session.lock:
            if session.closed:
                raise KeyError(sid)
            # same torn-read discipline as snapshot: generation leaves
            # the lock with the cells it labels
            generation = session.generation
            out = np.empty((h, w), dtype=np.uint8)
            if session.engine is not None:
                for out_r, out_c, r0, c0, rh, rw in rects:
                    part = session.engine.fetch_window(
                        session.grid, r0, c0, rh, rw, shard_timer=timer)
                    if part is None:
                        raise ConfigError(
                            "viewport over HTTP needs single-host "
                            "execution")
                    out[out_r:out_r + rh, out_c:out_c + rw] = part
            else:
                grid = np.asarray(session.grid, dtype=np.uint8)
                for out_r, out_c, r0, c0, rh, rw in rects:
                    out[out_r:out_r + rh,
                        out_c:out_c + rw] = grid[r0:r0 + rh, c0:c0 + rw]
            fl = obs.flight if obs is not None else None
            if fl is not None:
                fl.record("viewport", engine=session.engine,
                          session=sid, device_s=fetched["s"],
                          window=(x0, y0, h, w),
                          shards_touched=fetched["n"])
        return out, generation, session.config

    def write_board(self, sid: str, grid, generation: Optional[int] = None,
                    timeout_s: Optional[float] = None) -> dict:
        """Overwrite a live board's grid (the board-write endpoint).
        ``generation=None`` keeps the session's current generation;
        an explicit value rebases it (a client uploading a saved world).
        The written grid is persisted as a snapshot checkpoint
        immediately: replay-from-seed is no longer valid once a board
        has been written to, so durability must anchor on the write."""
        deadline = _Deadline(self._budget(timeout_s))
        return _watchdog_call(lambda: self._write_board(sid, grid, generation),
                              deadline, f"write_board({sid})")

    def _write_board(self, sid: str, grid,
                     generation: Optional[int]) -> dict:
        self._storage_gate(mutating=True)
        session = self.get(sid)
        arr = np.ascontiguousarray(grid, dtype=np.uint8)
        shape = (session.config.rows, session.config.cols)
        if arr.shape != shape:
            raise ConfigError(
                f"grid shape {arr.shape} does not match session "
                f"{shape[0]}x{shape[1]}")
        if arr.max(initial=0) > 1:
            raise ConfigError("grid cells must be 0 or 1")
        with session.lock:
            if session.closed:
                raise KeyError(sid)
            if session.engine is not None:
                # same entry point the restore path uses: the engine
                # re-stages the array (and resets any sparse dirty map)
                session.grid = session.engine.init_grid(
                    initial=arr, seed=session.config.seed)
            else:
                session.grid = arr
            if generation is not None:
                if generation < 0:
                    raise ConfigError(
                        f"generation must be >= 0, got {generation}")
                session.generation = int(generation)
            self._persist(session, grid_np=arr)
            out = {"id": sid, "generation": session.generation,
                   "rows": shape[0], "cols": shape[1], "written": True}
        if self.obs is not None:
            self.obs.event("board_write", sid=sid,
                           generation=out["generation"])
        self._notify_step(session)
        return out

    def write_window(self, sid: str, x0: int, y0: int, patch,
                     generation: Optional[int] = None,
                     timeout_s: Optional[float] = None) -> dict:
        """Write one region of a live board (the windowed board-write
        endpoint): only device shards intersecting the patch are
        fetched, edited, and re-put, so concurrent editors of disjoint
        regions never pay O(board).  ``generation`` follows the same
        rebase seam as :meth:`write_board`.  Like a full write, the
        result is persisted immediately (shard form on sharded
        engines): replay-from-seed is invalid once a board has been
        edited."""
        deadline = _Deadline(self._budget(timeout_s))
        return _watchdog_call(
            lambda: self._write_window(sid, x0, y0, patch, generation),
            deadline, f"write_window({sid})")

    def _write_window(self, sid: str, x0: int, y0: int, patch,
                      generation: Optional[int]) -> dict:
        self._storage_gate(mutating=True)
        session = self.get(sid)
        arr = np.ascontiguousarray(patch, dtype=np.uint8)
        if arr.ndim != 2:
            raise ConfigError(f"patch must be 2-D, got shape {arr.shape}")
        if arr.max(initial=0) > 1:
            raise ConfigError("grid cells must be 0 or 1")
        x0, y0 = int(x0), int(y0)
        rects = self.window_rects(x0, y0, arr.shape[0], arr.shape[1],
                                   session.config.rows,
                                   session.config.cols,
                                   session.config.boundary)
        with session.lock:
            if session.closed:
                raise KeyError(sid)
            if session.engine is not None:
                grid = session.grid
                for out_r, out_c, r0, c0, rh, rw in rects:
                    part = arr[out_r:out_r + rh, out_c:out_c + rw]
                    grid = session.engine.write_window(grid, r0, c0, part)
                    if grid is None:
                        break
                if grid is not None:
                    session.grid = grid
                else:
                    # sparse engines cannot edit in place (a partial
                    # edit would stale the dirty map): fall back to the
                    # full fetch-edit-reinit path
                    full = session.engine.fetch(session.grid)
                    if full is None:
                        raise ConfigError(
                            "board write over HTTP needs single-host "
                            "execution")
                    for out_r, out_c, r0, c0, rh, rw in rects:
                        full[r0:r0 + rh, c0:c0 + rw] = \
                            arr[out_r:out_r + rh, out_c:out_c + rw]
                    session.grid = session.engine.init_grid(
                        initial=full, seed=session.config.seed)
            else:
                grid = np.array(session.grid, dtype=np.uint8, copy=True)
                for out_r, out_c, r0, c0, rh, rw in rects:
                    grid[r0:r0 + rh, c0:c0 + rw] = \
                        arr[out_r:out_r + rh, out_c:out_c + rw]
                session.grid = grid
            if generation is not None:
                if generation < 0:
                    raise ConfigError(
                        f"generation must be >= 0, got {generation}")
                session.generation = int(generation)
            if self._sharded(session.engine):
                self._persist(session,
                              shards=session.engine.shard_snapshots(
                                  session.grid))
            elif session.engine is not None:
                self._persist(session,
                              grid_np=session.engine.fetch(session.grid))
            else:
                self._persist(session, grid_np=np.asarray(
                    session.grid, dtype=np.uint8))
            out = {"id": sid, "generation": session.generation,
                   "x0": x0, "y0": y0, "rows": int(arr.shape[0]),
                   "cols": int(arr.shape[1]), "written": True}
        if self.obs is not None:
            self.obs.event("board_write", sid=sid,
                           generation=out["generation"], x0=x0, y0=y0,
                           h=int(arr.shape[0]), w=int(arr.shape[1]))
        self._notify_step(session)
        return out

    def density(self, sid: str, timeout_s: Optional[float] = None) -> dict:
        deadline = _Deadline(self._budget(timeout_s))
        return _watchdog_call(lambda: self._density(sid), deadline,
                              f"density({sid})")

    def _density(self, sid: str) -> dict:
        session = self.get(sid)
        with session.lock:
            if session.closed:
                raise KeyError(sid)
            # same torn-read discipline as snapshot: the generation and
            # the population it describes leave the lock together
            generation = session.generation
            if session.engine is not None:
                pop = session.engine.population(session.grid)
            else:
                pop = int(np.asarray(session.grid, dtype=np.int64).sum())
        return {"id": sid, "generation": generation,
                "population": pop,
                "density": pop / session.config.cells}

    # -- introspection -----------------------------------------------------

    def describe(self, session: Session) -> dict:
        # snapshot every field under session.lock: a concurrent close()
        # nulls session.engine, and a concurrent step bumps generation —
        # reading them unlocked can tear (engine checked non-None, then
        # dereferenced as None)
        with session.lock:
            engine = session.engine
            d = {
                "id": session.id,
                "backend": session.config.backend,
                "rows": session.config.rows,
                "cols": session.config.cols,
                "rule": str(session.config.rule),
                "boundary": session.config.boundary,
                "generation": session.generation,
                "throughput": session.throughput(),
            }
            if engine is not None:
                d["cache_hit"] = session.cache_hit
                d["engine_compiles"] = engine.compile_count
                d["engine_batched_compiles"] = engine.batched_compile_count
                d["engine_notes"] = list(engine.notes)
                d["batched_steps"] = session.batched_steps
                if engine.sparse_plan is not None:
                    d["sparse"] = engine.sparse_stats(session.grid)
                if getattr(engine, "tuned_plan", None):
                    d["tuned_plan"] = dict(engine.tuned_plan)
            if session.degraded:
                d["degraded"] = True
                d["degraded_reason"] = session.degraded_reason
                d["active_backend"] = "serial_np"
            if session.restored:
                d["restored"] = True
            if session.last_error:
                d["last_error"] = session.last_error
            if session.tenant is not None:
                # armed admission only — unarmed payloads are unchanged
                d["tenant"] = session.tenant
                d["class"] = session.qos
        if self.dispatcher is not None:
            # read AFTER session.lock is released: the dispatch loop
            # takes session locks while holding its own, never reversed
            d["queue_depth"] = self.dispatcher.queued_for(session.id)
            d["tickets_pending"] = self.dispatcher.pending_for(session.id)
            d["tickets_completed"] = self.dispatcher.completed_for(session.id)
        if self.obs is not None:
            # the session's usage-ledger row (process-local metering;
            # absent until the first committed dispatch)
            usage = self.obs.ledger.session_row(session.id)
            if usage is not None:
                d["usage"] = usage
        return d

    def _session_list(self):
        with self._lock:
            return list(self._sessions.values())

    def stats(self) -> dict:
        with self._lock:
            sessions = list(self._sessions.values())
        out = {
            "cache": self.cache.stats(),
            "sessions": [self.describe(s) for s in sessions],
        }
        if self.batcher is not None:
            out["batch"] = self.batcher.stats()
        if self.dispatcher is not None:
            out["async"] = self.dispatcher.stats()
        out["breaker"] = self.cache.breaker_stats()
        out["failures"] = {
            "engine_failures": self.engine_failures,
            "watchdog_timeouts": self.watchdog_timeouts,
            "degraded_sessions": sum(1 for s in sessions if s.degraded),
            "degraded_total": self.degraded_total,
            "degrade_fallback": self.degrade,
        }
        if self.store is not None:
            rec = self.store.stats()
            rec["restored_sessions"] = self.restored_sessions
            rec["restore_errors"] = self.restore_errors
            rec["store_errors"] = self.store_errors
            out["recovery"] = rec
        if self.faults is not None:
            out["faults"] = self.faults.stats()
        if self.obs is not None:
            from mpi_tpu.obs.profile import compile_execute_breakdown

            obs_stats = self.obs.stats()
            obs_stats["breakdown"] = compile_execute_breakdown(self)
            obs_stats["usage"] = self.obs.ledger.totals()
            out["obs"] = obs_stats
        return out

    def usage(self) -> dict:
        """The ``GET /usage`` payload: ledger totals, per-session rows,
        and per-signature rows joined with each live engine's cost cards
        and a roofline readout (achieved cells/s over the cost-model
        bound).  Raises :class:`RuntimeError` when obs is off — the
        transport maps it to the same 404 as ``/metrics``.

        The ledger is process-local: a restart (or restore-from-
        checkpoint) starts metering from zero, by design."""
        if self.obs is None:
            raise RuntimeError("usage metering needs observability")
        from mpi_tpu.obs.cost import ops_per_cell_detail, roof_ops_per_s
        from mpi_tpu.obs.profile import _live_engines

        roof = roof_ops_per_s()
        ledger = self.obs.ledger
        signatures = ledger.signature_rows()
        by_label = {}
        for eng in _live_engines(self):
            label = getattr(eng, "sig_label", None)
            if label is not None and label not in by_label:
                by_label[label] = eng
        sig_rows = []
        for label in sorted(signatures):
            row = dict(signatures[label], signature=label)
            eng = by_label.get(label)
            if eng is not None:
                cards = eng.cost_cards()
                row["cost_cards"] = [c.as_dict() for c in cards]
                ops_per_cell, suspect = ops_per_cell_detail(
                    cards, eng.config.cells)
                if getattr(eng, "tuned_plan", None):
                    row["tuned_plan"] = dict(eng.tuned_plan)
                if ops_per_cell is not None and row["device_s"] > 0:
                    bound = roof / ops_per_cell
                    achieved = row["cells"] / row["device_s"]
                    row["roofline"] = {
                        "ops_per_cell": ops_per_cell,
                        "bound_cells_per_s": bound,
                        "achieved_cells_per_s": achieved,
                        "efficiency": achieved / bound,
                        # only depth>1 cards carried flops: XLA counts a
                        # while-loop body once, so the estimate may be
                        # low by up to the trip count
                        "trip_count_suspect": suspect,
                    }
            sig_rows.append(row)
        out = {
            "totals": ledger.totals(),
            "sessions": ledger.session_rows(),
            "signatures": sig_rows,
            "roof_ops_per_s": roof,
            "note": "process-local: restarts and restores reset nothing "
                    "but start metering from zero",
        }
        if self.cluster is not None:
            # slice-wide roll-up: local totals + each peer's latest
            # gossiped snapshot (exact sums, at most one interval stale)
            out["cluster"] = self.cluster.usage_rollup()
        if self.admission is not None:
            # spend vs quota, live sessions, class mix per tenant —
            # absent (not empty) on unarmed servers: the payload stays
            # byte-identical to pre-16
            out["tenants"] = self.admission.tenants_block()
        return out

    def slo(self) -> dict:
        """The ``GET /slo`` payload: the engine's full snapshot (states,
        burn rates, window summaries) plus the cluster roll-up when a
        node is attached.  The transport answers 404 before calling this
        when obs is off or telemetry is unarmed."""
        if self.obs is None or self.obs.slo is None:
            raise RuntimeError(
                "SLO evaluation needs --telemetry-interval-s")
        out = self.obs.slo.snapshot()
        if self.cluster is not None:
            # slice-wide roll-up: local compact state + each peer's
            # latest gossiped snapshot (same discipline as /usage)
            out["cluster"] = self.cluster.slo_rollup()
        return out

    def health(self) -> dict:
        """The deep ``/healthz`` payload.  ``ok`` is False — the probe
        answers 503 — exactly when the service is degraded with no
        fallback: some breaker is open and degradation is disabled, so
        requests on those plans cannot be served at all."""
        self.persistence_retry()        # the probe rides health checks too
        with self._lock:
            sessions = list(self._sessions.values())
        br = self.cache.breaker_stats()
        ok = not (br["open"] and not self.degrade)
        age = self.last_dispatch_age_s()
        age = round(age, 3) if age is not None else None
        out = {
            "ok": ok,
            "sessions": len(sessions),
            "tickets_pending": (self.dispatcher.pending()
                                if self.dispatcher is not None else 0),
            "degraded_sessions": sum(1 for s in sessions if s.degraded),
            "restored_sessions": self.restored_sessions,
            "breaker": {"open": br["open"], "half_open": br["half_open"],
                        "trips": br["trips"]},
            "degrade_fallback": self.degrade,
            "last_dispatch_ok_age_s": age,
            "state_dir": self.store.state_dir if self.store else None,
            "faults_injected": (sum(self.faults.injected.values())
                                if self.faults is not None else 0),
        }
        if self.store is not None:
            # the closed->degraded->recovering state machine, pending
            # backlog, and seconds to the next disk probe — always in
            # the body.  "ok" flips only when the degrade policy blocks
            # verbs (readonly/shed): under "continue" the node still
            # serves everything, and a 503 would make a balancer evict
            # a node that is working as designed
            pers = self.store.persistence_state()
            out["persistence"] = pers
            if pers["state"] == "degraded" \
                    and self.state_degrade != "continue":
                out["ok"] = False
        if self.obs is not None and self.obs.slo is not None:
            # alerting, not readiness: a burning SLO (even critical
            # availability) never flips "ok" — the probe keys readiness
            # on degraded-without-fallback, and restarting a process
            # because its error budget is gone only burns it faster
            out["slo"] = self.obs.slo.health_block()
        if self.cluster is not None:
            # peer liveness from gossip heartbeats.  Deliberately not
            # folded into "ok": a down peer makes ITS sessions 404, but
            # this process still serves everything it owns
            out["cluster"] = self.cluster.health_block()
            if self.cluster.draining:
                # drain flips the PROBE to 503 (the transport keys on
                # this) while the node keeps serving/proxying — exactly
                # what a load balancer needs to rotate it out
                out["draining"] = True
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)
