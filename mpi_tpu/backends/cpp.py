"""ctypes bindings for the native C++ engine (backends/native/golcore.cpp).

Two execution modes, mirroring the reference's two native programs:

* serial (``gol_evolve``) — the C++ oracle, the role of
  ``/root/reference/main_serial.cpp``;
* parallel (``gol_evolve_par``) — tile-decomposed multi-worker engine with
  explicit ghost-ring halo exchange, the shared-memory successor of the
  reference's MPI program (``/root/reference/main.cpp``); ``workers``
  plays the role of ``mpirun -np``.

The shared library is built on demand with ``make`` (g++, no external
deps); Python never reimplements the kernel — this is the native runtime
path, the JAX path is the TPU compute path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from mpi_tpu.models.rules import Rule, LIFE
from mpi_tpu.parallel.mesh import choose_mesh_shape

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libgolcore.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

_u8p = ctypes.POINTER(ctypes.c_uint8)


def _build() -> None:
    subprocess.run(
        ["make", "-C", _NATIVE_DIR],
        check=True,
        capture_output=True,
        text=True,
    )


def load_library() -> ctypes.CDLL:
    """Build (if needed) and load the native engine; idempotent."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        inputs = [os.path.join(_NATIVE_DIR, f) for f in ("golcore.cpp", "Makefile")]
        if not os.path.exists(_SO_PATH) or os.path.getmtime(_SO_PATH) < max(
            os.path.getmtime(p) for p in inputs
        ):
            _build()
        lib = ctypes.CDLL(_SO_PATH)
        lib.gol_init.argtypes = [
            _u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_uint32,
            ctypes.c_int64, ctypes.c_int64,
        ]
        lib.gol_init.restype = None
        lib.gol_step.argtypes = [
            _u8p, _u8p, ctypes.c_int64, ctypes.c_int64, _u8p, _u8p,
            ctypes.c_int, ctypes.c_int,
        ]
        lib.gol_step.restype = None
        lib.gol_evolve.argtypes = [
            _u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, _u8p, _u8p,
            ctypes.c_int, ctypes.c_int,
        ]
        lib.gol_evolve.restype = None
        lib.gol_evolve_par.argtypes = [
            _u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, _u8p, _u8p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.gol_evolve_par.restype = ctypes.c_int
        _lib = lib
        return lib


def _as_u8p(a: np.ndarray):
    return a.ctypes.data_as(_u8p)


def _check_grid(grid: np.ndarray) -> np.ndarray:
    if grid.dtype != np.uint8 or grid.ndim != 2:
        raise ValueError(f"grid must be 2D uint8, got {grid.dtype} {grid.shape}")
    return np.ascontiguousarray(grid)


def init_tile_cpp(
    rows: int, cols: int, seed: int, row_offset: int = 0, col_offset: int = 0
) -> np.ndarray:
    lib = load_library()
    out = np.empty((rows, cols), dtype=np.uint8)
    lib.gol_init(_as_u8p(out), rows, cols, seed & 0xFFFFFFFF, row_offset, col_offset)
    return out


def step_cpp(grid: np.ndarray, rule: Rule = LIFE, boundary: str = "periodic") -> np.ndarray:
    lib = load_library()
    grid = _check_grid(grid)
    bt, st = rule.tables()
    out = np.empty_like(grid)
    lib.gol_step(
        _as_u8p(grid), _as_u8p(out), grid.shape[0], grid.shape[1],
        _as_u8p(bt), _as_u8p(st), rule.radius, 1 if boundary == "periodic" else 0,
    )
    return out


def evolve_cpp(
    grid: np.ndarray, steps: int, rule: Rule = LIFE, boundary: str = "periodic"
) -> np.ndarray:
    """Serial native evolution (the C++ oracle)."""
    lib = load_library()
    out = _check_grid(grid).copy()
    bt, st = rule.tables()
    lib.gol_evolve(
        _as_u8p(out), out.shape[0], out.shape[1], steps,
        _as_u8p(bt), _as_u8p(st), rule.radius, 1 if boundary == "periodic" else 0,
    )
    return out


def plan_tiles(shape: Tuple[int, int], workers: int, radius: int) -> Tuple[int, int]:
    """Largest worker-tile mesh with <= workers tiles that divides the grid
    and keeps each tile at least radius cells per side (the native engine's
    ghost slabs are filled from a single neighbor)."""
    if workers <= 0:
        workers = min(os.cpu_count() or 1, 16)
    ti, tj = choose_mesh_shape(workers)
    while shape[0] % ti or shape[1] % tj or \
            shape[0] // ti < radius or shape[1] // tj < radius:
        workers -= 1
        if workers <= 1:
            return (1, 1)
        ti, tj = choose_mesh_shape(workers)
    return ti, tj


def evolve_par_cpp(
    grid: np.ndarray,
    steps: int,
    rule: Rule = LIFE,
    boundary: str = "periodic",
    workers: int = 0,
    tiles: Optional[Tuple[int, int]] = None,
) -> np.ndarray:
    """Multi-worker native evolution over a tile mesh (one thread per tile)."""
    lib = load_library()
    out = _check_grid(grid).copy()
    if tiles is None:
        ti, tj = plan_tiles(out.shape, workers, rule.radius)
    else:
        ti, tj = tiles
    bt, st = rule.tables()
    rc = lib.gol_evolve_par(
        _as_u8p(out), out.shape[0], out.shape[1], steps,
        _as_u8p(bt), _as_u8p(st), rule.radius, 1 if boundary == "periodic" else 0,
        ti, tj,
    )
    if rc != 0:
        raise ValueError(
            f"native engine rejected tile mesh {ti}x{tj} for grid {out.shape} "
            f"radius {rule.radius} (rc={rc})"
        )
    return out
