"""Execution backends: numpy serial oracle, native C++ engines, TPU."""
