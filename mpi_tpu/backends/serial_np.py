"""Pure-numpy serial oracle — the known-good baseline every other backend
is tested against (the role ``/root/reference/main_serial.cpp`` plays for
the reference, SURVEY.md §4.1).

Deliberately implemented with a *different algorithm* from the JAX path
(`mpi_tpu.ops.stencil` uses separable window sums + interval compares;
this uses a full non-separable shifted-add sum + rule table lookup) so the
cross-backend parity tests compare independent derivations, not the same
code twice.

Fixes vs the reference oracle, documented for parity auditing:
* boundary is a flag (reference serial is periodic-only, ``main_serial.cpp:57``);
* no init/update index mismatch (reference quirk #3: init fills [0,n) while
  update reads [1,n], leaving an uninitialized edge);
* init is the shared decomposition-invariant hash, not ``srand`` sequences.
"""

from __future__ import annotations

import numpy as np

from mpi_tpu.models.rules import Rule, LIFE
from mpi_tpu.utils.hashinit import init_tile_np


def counts_np(grid: np.ndarray, radius: int, boundary: str) -> np.ndarray:
    """Neighbor counts (center excluded), full (2r+1)² shifted-add sum."""
    r = radius
    if boundary == "periodic":
        p = np.pad(grid, r, mode="wrap")
    elif boundary == "dead":
        p = np.pad(grid, r, mode="constant")
    else:
        raise ValueError(f"unknown boundary {boundary!r}")
    H, W = grid.shape
    c = np.zeros((H, W), dtype=np.uint8)
    for di in range(2 * r + 1):
        for dj in range(2 * r + 1):
            if di == r and dj == r:
                continue
            c += p[di : di + H, dj : dj + W]
    return c


def step_np(grid: np.ndarray, rule: Rule = LIFE, boundary: str = "periodic") -> np.ndarray:
    """One generation, via rule lookup tables."""
    c = counts_np(grid, rule.radius, boundary)
    birth_table, survive_table = rule.tables()
    alive = grid.astype(bool)
    return np.where(alive, survive_table[c], birth_table[c]).astype(np.uint8)


def evolve_np(
    grid: np.ndarray,
    steps: int,
    rule: Rule = LIFE,
    boundary: str = "periodic",
) -> np.ndarray:
    for _ in range(steps):
        grid = step_np(grid, rule, boundary)
    return grid


def run_serial(config) -> np.ndarray:
    """Init + evolve per a GolConfig; returns the final grid."""
    grid = init_tile_np(config.rows, config.cols, config.seed)
    return evolve_np(grid, config.steps, config.rule, config.boundary)
