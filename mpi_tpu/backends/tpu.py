"""TPU backend runner: sharded init → compiled segment evolution →
snapshot/checkpoint hooks.

This is the driver loop of the reference (``/root/reference/main.cpp:
291-305``) restructured for XLA: instead of [update → barrier → halo →
maybe-dump] per step on the host, the whole inter-snapshot segment is one
compiled ``scan`` (halo ppermutes and stencil fused inside), and the host
only touches data at snapshot boundaries.  Compilation is accounted as
"setup" (the reference's topology+alloc phase, ``main.cpp:233-289``) so
the timing reports stay comparable.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np
import jax

from mpi_tpu.config import GolConfig, plan_segments
from mpi_tpu.parallel.mesh import make_mesh
from mpi_tpu.parallel.step import grid_sharding, make_sharded_stepper, sharded_init
from mpi_tpu.utils.segmenting import segment_depths
from mpi_tpu.utils.timing import PhaseTimer

SnapshotCb = Callable[[int, List[Tuple[int, np.ndarray, int, int]]], None]


def _batch_width(grids) -> int:
    """Leading (board) axis of a stacked batch — pytree-safe, because a
    sparse engine's batch is a stacked SparseState, not a bare array."""
    return int(jax.tree_util.tree_leaves(grids)[0].shape[0])
# snapshot_cb(iteration, [(pid, tile, first_row, first_col), ...]) —
# pids are globally unique (row-major over the global tile grid), so each
# host of a multi-host run can write its own shards without collisions.


def _shard_tiles(grid: jax.Array,
                 col_limit=None) -> List[Tuple[int, np.ndarray, int, int]]:
    """(pid, tile, first_row, first_col) for every *addressable* shard —
    each device's shard becomes one .gol tile, the way each MPI rank wrote
    its own tile in the reference (``main.cpp:106-129``).  The pid is the
    row-major index of the shard's position in the global tile grid, so it
    is globally unique even when multiple hosts each dump only their own
    addressable shards.

    ``col_limit``: real grid width of a pad-to-32 run — tiles are cropped
    to it (a tile that lies entirely in the pad is dropped; its pid is
    simply absent, which the snapshot readers tolerate because coverage
    is judged against the real width)."""
    shards = []
    for s in grid.addressable_shards:
        r0 = s.index[0].start or 0
        c0 = s.index[1].start or 0
        shards.append((np.asarray(s.data), r0, c0))
    if not shards:
        return []
    th, tw = shards[0][0].shape
    tiles_j = grid.shape[1] // tw
    out = []
    for tile, r0, c0 in shards:
        if col_limit is not None:
            if c0 >= col_limit:
                continue
            tile = tile[:, : col_limit - c0]
        out.append(((r0 // th) * tiles_j + (c0 // tw), tile, r0, c0))
    out.sort(key=lambda t: t[0])
    return out


def _pallas_single_device_mode():
    """(use, interpret) for the fused-kernel dispatch — single-device
    steppers AND the sharded steppers' fused tile interiors: a real TPU
    runs the kernels natively; off-TPU the kernels are only taken when
    MPI_TPU_PALLAS_INTERPRET=1 (tests) — interpret-mode Pallas is far too
    slow for production runs, which keep the compiled XLA path.  (The
    name predates the sharded fusion; kept stable for callers/tests.)"""
    import os

    if jax.devices()[0].platform == "tpu":
        return True, False
    return os.environ.get("MPI_TPU_PALLAS_INTERPRET") == "1", True


def plan_pad_width(config: GolConfig, mj: int, fused_capable=None,
                   shard_rows=None):
    """(cols_padded, pad_bits) — the pad-to-32 plan (VERDICT r3 item 3).

    A dead-boundary grid whose shard width is not word-aligned is padded
    with trailing dead columns to the next word multiple per shard, so
    the run rides the packed engines (XLA SWAR / bit-sliced LtL, ~6-25×
    the dense engines) instead of silently falling to dense; the
    steppers re-kill the pad every generation (``pad_bits``) and
    snapshots/results crop back to the real width.  At ``comm_every==1``
    with modest waste the pad stretches to lane alignment (4096 cells
    per shard) so the fused Pallas interior qualifies too — but only
    when the platform can actually run it (``fused_capable``, defaulting
    to the Pallas platform gate) AND, when ``shard_rows`` is supplied,
    the kernel's shape predicate accepts the stretched shard: off-TPU or
    on a kernel-rejected shape the stretch would compute up to 25% extra
    columns the XLA engine gets nothing for.

    PERIODIC grids pad too (VERDICT r4 item 5): the wrap cannot cross a
    misaligned word boundary in word arithmetic, but the padded periodic
    stepper's column wrap reads the re-killed pad (zeros), so only the
    ``d = comm_every·r`` columns around the seam are wrong — and
    ``parallel.seam.make_seam_stepper`` recomputes exactly those with a
    dense true-periodic band and stitches them in.  Refused only when
    the band cannot serve: d > 31 (mask/word-column bound) or width
    < 4d (the strip would wrap onto itself) — those keep the dense
    engine.
    """
    from mpi_tpu.ops.bitlife import WORD

    shard = config.cols // mj
    if shard % WORD == 0:
        return config.cols, 0
    if config.boundary == "periodic":
        from mpi_tpu.parallel.seam import seam_serves

        if not seam_serves(config.cols,
                           config.comm_every * config.rule.radius):
            return config.cols, 0
    cp_shard = -(-shard // WORD) * WORD
    if fused_capable is None:
        fused_capable = _pallas_single_device_mode()[0]
    if config.comm_every == 1 and fused_capable:
        lane = -(-shard // 4096) * 4096
        if lane * mj <= int(1.25 * config.cols):
            ok = True
            if shard_rows is not None:
                from mpi_tpu.parallel.step import (
                    bit_local_pallas_ok, ltl_local_pallas_ok,
                )

                pred = (bit_local_pallas_ok if config.rule.radius == 1
                        else ltl_local_pallas_ok)
                ok = pred((shard_rows, lane // WORD), config.rule, 1)
            if ok:
                cp_shard = lane
    return cp_shard * mj, cp_shard * mj - config.cols


def _shard_shape_packed(config: GolConfig, mesh, cols=None):
    """Per-shard packed (rows, words) under the mesh; ``cols`` overrides
    the config's width (the padded width of a pad-to-32 run)."""
    from mpi_tpu.ops.bitlife import WORD
    from mpi_tpu.parallel.mesh import AXES

    cols = config.cols if cols is None else cols
    mi, mj = mesh.shape[AXES[0]], mesh.shape[AXES[1]]
    return config.rows // mi, (cols // mj) // WORD


def _pick_packed_evolve(config: GolConfig, mesh, n_devices: int,
                        cols=None, pad_bits: int = 0, depths=None,
                        seam_pad: bool = False, overlap=None,
                        blocks=None):
    """(stepper, used_pallas) for the packed engine: on a single device
    the fused Pallas SWAR kernel (ops/pallas_bitlife.py) replaces the
    shard_map/XLA path — no halo exchange exists, ``comm_every`` becomes
    the kernel's temporal-blocking depth (generations per HBM
    round-trip), and a requested ``overlap`` is vacuous (no collective
    to overlap with), so the fused kernel is taken regardless of the
    flag.  Multi-device meshes keep the ppermute stepper but run the
    tile *interior* through the same fused kernel when on TPU (VERDICT
    r3 item 1: per-chip throughput must not drop ~6.5× the moment a
    mesh appears); shard shapes the kernel cannot serve — and off-TPU
    production runs — fall back to the XLA local compute inside the
    same stepper."""
    from mpi_tpu.parallel.step import (
        bit_local_pallas_ok, make_sharded_bit_stepper,
    )

    if overlap is None:
        overlap = config.overlap
    use, interpret = _pallas_single_device_mode()
    if n_devices == 1 and not pad_bits:
        # (padded runs skip the bare single-device kernel: the pad must
        # be re-killed between generations, which only the sharded
        # stepper's mask discipline does — a 1x1 mesh serves them)
        from mpi_tpu.ops.pallas_bitlife import make_pallas_bit_stepper, supports

        gens = config.comm_every
        shape = (config.rows, config.cols)
        # (birth-on-0 with gens > 1 is already rejected by GolConfig)
        if use and supports(shape, config.rule, gens=gens):
            return make_pallas_bit_stepper(
                config.rule, config.boundary, interpret=interpret,
                gens=gens,
                blocks=tuple(blocks) if blocks is not None else None,
            ), True
    stepper = make_sharded_bit_stepper(
        mesh, config.rule, config.boundary,
        gens_per_exchange=config.comm_every, overlap=overlap,
        use_pallas=use, pallas_interpret=interpret, pad_bits=pad_bits,
        seam_pad=seam_pad,
    )
    # the compile-fallback must treat the stepper as Pallas-bearing iff
    # a depth that will actually be traced takes the fused interior;
    # padded runs take it only at depth 1
    shard = _shard_shape_packed(config, mesh, cols)
    if depths is None:
        depths = range(1, config.comm_every + 1)  # conservative superset
    if pad_bits:
        depths = [k for k in depths if k == 1]
    used = use and any(
        bit_local_pallas_ok(shard, config.rule, k) for k in depths
    )
    return stepper, used


def select_ltl_mode(config: GolConfig, mi: int, mj: int, cols=None,
                    pad_bits: int = 0):
    """Engine choice for a radius > 1 rule: ``("pallas" | "sharded" |
    None, note)``.  None means the dense path serves the run; ``note``
    (when set) explains a fallback off the fast bit-sliced engine so the
    user sees why their run is on the slow path instead of a silent
    ~3.6x cliff (ADVICE r2: tpu.py:212).  Pure dispatch — no devices
    touched beyond the platform gate — so tests can pin the policy.
    ``cols``/``pad_bits``: the pad-to-32 plan (non-word-aligned dead
    runs arrive here with the padded width and route onto the
    bit-sliced engine; padded single-device runs use the 1x1-mesh
    sharded stepper, whose mask discipline the bare kernel lacks)."""
    r = config.rule.radius
    cols = config.cols if cols is None else cols
    if r <= 1:
        return None, None
    if (cols // mj) % 32 != 0:
        # plan_pad_width declined to pad.  The note names the config's
        # actual boundary (ADVICE r5): only periodic runs have a seam
        # gate to explain — on a dead boundary a misaligned width landing
        # here must not claim "periodic … seam stitching" (tiny grids are
        # exactly where dense is fine either way)
        note = (
            f"radius-{r} rule on non-word-aligned shard width "
            f"({config.cols}/{mj} cols per shard), {config.boundary}: "
            f"dense engine"
        )
        if config.boundary == "periodic":
            note += (
                f" (seam stitching needs comm_every*radius <= 31 and "
                f"width >= {4 * config.comm_every * r})"
            )
        return None, note
    if mi * mj == 1 and not pad_bits and _ltl_single_device(config):
        return "pallas", None
    if config.comm_every * r > 31:
        return None, (
            f"comm_every {config.comm_every} x radius {r} > 31 exceeds the "
            f"one-ghost-word halo: dense engine (~3.6x slower at r=5; use "
            f"comm_every <= {31 // r} to keep the bit-sliced engine)"
        )
    if mi * mj > 1:
        return "sharded", None
    # padded single device on TPU: the 1x1-mesh sharded stepper carries
    # the per-generation pad mask the bare kernel lacks (its fused
    # interior still engages at depth 1)
    if pad_bits and _pallas_single_device_mode()[0]:
        return "sharded", None
    # single device + comm_every > 1: the sharded stepper on a 1x1 mesh
    # (self-wrapping exchange, fused bit-sliced interior in chunks) beats
    # dense on shapes its kernel serves — but when that kernel's lane
    # contract fails AND the fused DENSE stencil kernel can temporally
    # block the whole segment in one pallas_call (gens·r ≤ 16), dense is
    # no longer the XLA slow path and takes the run; off-TPU production
    # keeps dense either way (bit-sliced measured slower on CPU at r=5)
    if config.comm_every > 1 and _pallas_single_device_mode()[0]:
        from mpi_tpu.ops.pallas_stencil import supports as _dense_supports
        from mpi_tpu.parallel.step import ltl_local_pallas_ok

        if (not ltl_local_pallas_ok((config.rows, cols // 32), config.rule, 1)
                and _dense_supports((config.rows, cols), config.rule,
                                    gens=config.comm_every)):
            return None, None
        return "sharded", None
    if config.comm_every > 1:
        return None, (
            f"radius-{r} with comm_every > 1 off-TPU: dense engine "
            f"(bit-sliced measured slower than dense on CPU)"
        )
    if _pallas_single_device_mode()[0]:
        # on-TPU the fused kernel declined on shape alone — a real perf
        # cliff worth naming
        return None, (
            f"radius-{r} fused kernel unavailable for this shape: "
            f"dense engine"
        )
    # off-TPU single device at comm_every == 1: dense IS the intended
    # (measured-faster) path there — not a degradation, no note
    return None, None


def _ltl_single_device(config: GolConfig) -> bool:
    """Serve a radius > 1 rule with the fused bit-sliced LtL kernel
    (ops/pallas_bitltl.py)?  Single-device, packable width, comm_every
    within the kernel's temporal-blocking depth (gens ≤ ⌊8/r⌋ — so
    r ≥ 5 only at comm_every 1), and the same TPU gating as the other
    Pallas dispatches.  Measured (PERF.md): 124 Gcell/s for Bosco vs 34
    for the best dense engine."""
    from mpi_tpu.ops.pallas_bitltl import supports

    if not supports((config.rows, config.cols), config.rule,
                    gens=config.comm_every):
        return False
    use, _ = _pallas_single_device_mode()
    return use


def _pick_dense_evolve(config: GolConfig, mesh, n_devices: int,
                       depths=None, blocks=None):
    """(stepper, used_pallas) for the dense engine: the fused dense
    Pallas kernel (ops/pallas_stencil.py, one HBM read + one write per
    cell per *segment* via temporal blocking) replaces the shard_map/XLA
    path wherever its contract holds, which would otherwise serve a
    higher-radius run with the slowest engine.

    Single device: comm_every = K > 1 runs K generations in ONE
    ``pallas_call`` (gens=K temporal blocking, bounded by K·r ≤ 16);
    ``overlap`` is vacuous (no collective to overlap with — same
    contract as the packed engine) and does not affect the dispatch.
    ``blocks`` threads the tuner's (BM, SR) override.

    Multi-device meshes: the ppermute stepper, with the fused kernel
    serving each tile's *interior* (``use_pallas`` — the stitched-band
    overlap structure) where :func:`dense_local_pallas_ok` accepts the
    shard shape at every traced segment depth; ``used_pallas`` reports
    whether any depth can take the kernel (the per-shape fallback keeps
    the rest correct).  Off-TPU production runs stay pure XLA."""
    from mpi_tpu.parallel.mesh import AXES
    from mpi_tpu.parallel.step import dense_local_pallas_ok

    use, interpret = _pallas_single_device_mode()
    if n_devices == 1:
        from mpi_tpu.ops.pallas_stencil import make_pallas_stepper, supports

        if use and supports((config.rows, config.cols), config.rule,
                            gens=config.comm_every):
            return make_pallas_stepper(
                config.rule, config.boundary, interpret=interpret,
                gens=config.comm_every,
                blocks=tuple(blocks) if blocks else None,
            ), True
        return make_sharded_stepper(
            mesh, config.rule, config.boundary,
            gens_per_exchange=config.comm_every, overlap=config.overlap,
        ), False
    mi = mesh.shape[AXES[0]]
    mj = mesh.shape[AXES[1]]
    shard = (config.rows // mi, config.cols // mj)
    kset = tuple(depths) if depths else (config.comm_every,)
    used = use and any(
        dense_local_pallas_ok(shard, config.rule, k) for k in kset
    )
    return make_sharded_stepper(
        mesh, config.rule, config.boundary,
        gens_per_exchange=config.comm_every, overlap=config.overlap,
        use_pallas=use, pallas_interpret=interpret,
    ), used


def _put_initial(mesh, initial, rows: int, cols: int, packed: bool,
                 col_limit=None):
    """Place a checkpoint grid onto the mesh sharding.

    ``initial`` is either a host-global (rows, real-cols) uint8 array or
    a region loader ``f(r0, r1, c0, c1) -> uint8 array`` (multihost
    resume: no host can hold — or even read — the whole grid, so each
    host loads exactly its addressable shards and the global array is
    assembled with ``jax.make_array_from_single_device_arrays``).

    ``col_limit``: the real grid width of a pad-to-32 run — ``cols`` is
    then the padded width, and columns ≥ the limit are zero-filled
    instead of loaded (the checkpoint only covers real cells)."""
    from mpi_tpu.ops.bitlife import WORD, pack_np
    from mpi_tpu.parallel.step import grid_sharding

    if callable(initial):
        loader = initial
    else:
        arr = np.asarray(initial, dtype=np.uint8)

        def loader(r0, r1, c0, c1):
            return arr[r0:r1, c0:c1]

    if col_limit is not None:
        real_loader = loader

        def loader(r0, r1, c0, c1):
            out = np.zeros((r1 - r0, c1 - c0), dtype=np.uint8)
            if c0 < col_limit:
                cc1 = min(c1, col_limit)
                out[:, : cc1 - c0] = real_loader(r0, r1, c0, cc1)
            return out

    sharding = grid_sharding(mesh)
    gshape = (rows, cols // WORD) if packed else (rows, cols)
    arrays = []
    for dev, idx in sharding.addressable_devices_indices_map(gshape).items():
        r0, r1 = idx[0].start or 0, idx[0].stop or gshape[0]
        c0, c1 = idx[1].start or 0, idx[1].stop or gshape[1]
        if packed:
            tile = pack_np(loader(r0, r1, c0 * WORD, c1 * WORD))
        else:
            tile = np.asarray(loader(r0, r1, c0, c1), dtype=np.uint8)
        arrays.append(jax.device_put(tile, dev))
    return jax.make_array_from_single_device_arrays(gshape, sharding, arrays)


class Engine:
    """A compiled stepper bound to one plan signature.

    Everything ``run_tpu`` used to set up inline — pad-to-32 planning,
    engine dispatch, seam wrapping, compile fallback — factored into an
    object that outlives one run: ``mpi_tpu.serve`` keeps Engines in an
    LRU cache (``serve/cache.py``) so a second board with the same plan
    signature reuses the compiled executables instead of paying the
    XLA/Mosaic compile again.  ``run_tpu`` is a thin one-shot wrapper.

    Grid state lives OUTSIDE the engine — every method takes/returns it —
    so any number of sessions can share one engine.  Segment executables
    compile lazily per distinct length and memoize in ``_compiled``;
    ``compile_count`` counts real XLA compiles (the serve layer's
    zero-recompile-on-cache-hit assertion reads it).

    Batched stepping (the serve layer's microbatch scheduler): a stacked
    ``[B, ...]`` batch of same-plan boards advances through ONE device
    dispatch via ``step_batched`` — ``jax.vmap`` over the board axis of
    the same evolve program (seam/halo logic is per-board, so vmap
    composes with the sharded steppers; the batch axis is replicated
    over the mesh while each board keeps the usual (i, j) sharding).
    Small boards are dispatch-bound (~68 ms fixed per call over the
    tunnel, PERF.md), so B boards per call amortize that fixed cost to
    68/B ms per board.  Batched executables memoize per ``(depth, B)``
    in ``_compiled_batched`` with the same donation and Pallas
    compile-fallback discipline as the solo table;
    ``step_calls``/``batched_step_calls`` count device dispatches (the
    scheduler's one-dispatch-per-coalesced-batch assertion reads them)."""

    def __init__(self, config: GolConfig, mesh, evolve, *, bitpacked: bool,
                 cols_eff: int, pad_bits: int, used_pallas: bool,
                 fallback_factory, notes=(), sparse_plan=None):
        from mpi_tpu.parallel.mesh import AXES

        self.config = config
        self.mesh = mesh
        self.mi, self.mj = mesh.shape[AXES[0]], mesh.shape[AXES[1]]
        self.bitpacked = bitpacked
        self.cols_eff = cols_eff
        self.pad_bits = pad_bits
        self.notes = tuple(notes)
        # activity-gated sparse stepping (ops/activity.py): when set, the
        # "grid" every step method passes around is a SparseState pytree
        # (grid + dirty-tile map); fetch/population/snapshot paths unwrap
        # via raw_grid, everything else is opaque
        self.sparse_plan = sparse_plan
        self._evolve = evolve
        self._used_pallas = used_pallas
        self._fallback_factory = fallback_factory
        self._compiled = {}
        self._compiled_batched = {}
        self._evolve_batched = None
        self._stack_fn = None
        self._unstack_fn = None
        self._compile_lock = threading.Lock()
        self.compile_count = 0
        self.batched_compile_count = 0
        self.compile_wall_s = 0.0
        self.step_calls = 0
        self.batched_step_calls = 0
        self._unpacker = None
        # optional callable(site) invoked just before each device dispatch
        # ('step' | 'batched'); the serve layer installs its fault injector
        # here so recovery paths are testable without sick hardware
        self.fault_hook = None
        # optional mpi_tpu.obs.Obs handle installed by the serve layer;
        # only consulted on the compile (miss) path — the per-dispatch
        # hot path stays untouched so obs=None is the pre-obs code
        self.obs = None
        # cost-card state (obs/cost.py): the serve layer stamps the
        # compact plan tag (sig_label) next to obs; cards are captured
        # per (depth, B) on real compile misses, only when obs is
        # installed — obs=None engines never pay the analysis/retrace
        self.sig_label = None
        self._cost_cards = {}
        # autotuner provenance (mpi_tpu/tune): the applied plan-override
        # dict when build_engine resolved this engine through a tune
        # cache, None on the default build path.  Read by the obs layer
        # (mpi_tpu_tuned_plans, the plan="tuned" dispatch series) and
        # /stats describe rows; never consulted by the step path.
        self.tuned_plan = None

    @property
    def col_limit(self):
        """Real grid width of a padded run (None when nothing is padded)."""
        return self.config.cols if self.pad_bits else None

    @property
    def donates_input(self) -> bool:
        """Whether this engine's steppers donate their input grid.

        Seam-stitched programs (padded periodic, see make_seam_stepper)
        must NOT donate: the band extraction reads the pre-step grid the
        base step would alias in place, which races on multi-device
        meshes.  Everything else must donate — losing it silently doubles
        peak HBM per session.  The IR verifier
        (``python -m mpi_tpu.analysis.ir``) holds the lowered IR to this
        contract in both directions."""
        return not (self.pad_bits > 0 and self.config.boundary == "periodic")

    def init_grid(self, initial=None, seed=None):
        """A fresh device-resident grid on this engine's mesh/sharding.
        ``seed`` overrides config.seed: serve sessions share one engine
        across seeds (the seed is deliberately not in the plan key).
        Sparse engines return a SparseState (every tile marked dirty —
        the first steps probe and settle the gate on their own)."""
        seed = self.config.seed if seed is None else seed
        if self.bitpacked:
            from mpi_tpu.parallel.step import sharded_bit_init

            if initial is not None:
                grid = _put_initial(self.mesh, initial, self.config.rows,
                                    self.cols_eff, True,
                                    col_limit=self.col_limit)
            else:
                grid = sharded_bit_init(self.mesh, self.config.rows,
                                        self.cols_eff, seed,
                                        col_limit=self.col_limit)
        elif initial is not None:
            grid = _put_initial(self.mesh, initial, self.config.rows,
                                self.config.cols, False)
        else:
            grid = sharded_init(self.mesh, self.config.rows,
                                self.config.cols, seed)
        if self.sparse_plan is not None:
            from mpi_tpu.ops.activity import initial_state

            return initial_state(grid, self.sparse_plan)
        return grid

    def raw_grid(self, grid):
        """The bare device array behind a step-state (identity on dense
        engines; unwraps the SparseState of a sparse engine) — for
        callers that need array attributes (shards, shape)."""
        if self.sparse_plan is not None:
            return grid.grid
        return grid

    def sparse_stats(self, grid) -> Optional[dict]:
        """Activity readout (active_tiles/active_fraction/mode) of a
        sparse engine's state; None on dense engines.  Costs a tiny
        device reduce over the nti x ntj tile map plus one fetch."""
        if self.sparse_plan is None:
            return None
        from mpi_tpu.ops.activity import activity_stats

        return activity_stats(grid, self.sparse_plan)

    def ensure_compiled(self, grid, n: int):
        """The compiled executable advancing ``grid`` by ``n`` generations
        (lazily lowered + compiled, memoized).  A fused Pallas kernel that
        fails to COMPILE (Mosaic register allocation, a VMEM shape outside
        the calibrated map) degrades to the always-available shard_map/XLA
        stepper instead of killing the run; if the dispatch never chose a
        Pallas kernel the error is real — re-raise rather than pay a
        second identical compile under a misleading note."""
        c = self._compiled.get(n)
        if c is not None:
            return c
        # serve sessions share one engine across HTTP handler threads; a
        # race here would double-compile AND double-count (the cache's
        # zero-recompile assertion reads compile_count)
        with self._compile_lock:
            c = self._compiled.get(n)
            if c is not None:
                return c
            t0 = time.perf_counter()
            c = self._compile_with_fallback(
                lambda: self._evolve.lower(grid, n).compile())
            dt = time.perf_counter() - t0
            self._compiled[n] = c
            self.compile_count += 1
            self.compile_wall_s += dt
            if self.obs is not None:
                self.obs.compile_wall.observe(dt)
                self.obs.event("compile", dt, t0, depth=n)
                self._capture_cost_card(
                    c, n, 0,
                    lambda: jax.make_jaxpr(
                        lambda g: self._evolve(g, n))(grid))
            return c

    def ensure_compiled_batched(self, grids, n: int):
        """Batched analog of :meth:`ensure_compiled`: the executable
        advancing a stacked ``[B, ...]`` batch by ``n`` generations,
        memoized per ``(n, B)`` with the same lock/fallback/counting
        discipline (``compile_count`` covers both tables — the serve
        layer's zero-recompile assertions read one counter)."""
        key = (n, _batch_width(grids))
        c = self._compiled_batched.get(key)
        if c is not None:
            return c
        with self._compile_lock:
            c = self._compiled_batched.get(key)
            if c is not None:
                return c
            t0 = time.perf_counter()
            c = self._compile_with_fallback(
                lambda: self._get_batched_evolve().lower(grids, n).compile())
            dt = time.perf_counter() - t0
            self._compiled_batched[key] = c
            self.compile_count += 1
            self.batched_compile_count += 1
            self.compile_wall_s += dt
            if self.obs is not None:
                self.obs.compile_wall.observe(dt)
                self.obs.event("compile", dt, t0, depth=n, B=key[1])
                self._capture_cost_card(
                    c, n, key[1],
                    lambda: jax.make_jaxpr(
                        lambda g: self._get_batched_evolve()(g, n))(grids))
            return c

    def _compile_with_fallback(self, compile_fn):
        try:
            return compile_fn()
        except Exception as e:  # noqa: BLE001 — Mosaic/VMEM errors vary by version
            if not self._used_pallas:
                raise
            import sys

            print(
                f"note: fused kernel failed to compile "
                f"({type(e).__name__}: {str(e)[:200]}); falling back to the "
                f"XLA stepper",
                file=sys.stderr,
            )
            self._evolve = self._fallback_factory()
            self._used_pallas = False
            # drop Pallas-built executables so every depth reruns through
            # the one fallback stepper (outputs are bit-identical either
            # way — the parity suite proves it — but one program is easier
            # to reason about than a mixed table); the batched table vmaps
            # over _evolve, so it must drop and re-derive too
            self._compiled.clear()
            self._compiled_batched.clear()
            self._evolve_batched = None
            # the cards described the Pallas-built executables; the
            # re-capture on each table's next miss replaces them
            self._cost_cards.clear()
            return compile_fn()

    def _capture_cost_card(self, compiled, depth: int, batch: int,
                           trace_thunk) -> None:
        """Best-effort CostCard for a fresh executable (obs/cost.py) —
        caller holds ``_compile_lock`` and already checked ``self.obs``.
        Capture only reads the compiled artifact (and, when XLA reports
        no flops, retraces the stepper once on the miss path); a card
        that cannot be built is dropped, never an engine error."""
        try:
            from mpi_tpu.obs.cost import capture_card

            self._cost_cards[(depth, batch)] = capture_card(
                compiled, sig_label=self.sig_label, depth=depth,
                batch=batch, trace_thunk=trace_thunk)
        except Exception:  # noqa: BLE001 — metering must never break serving
            pass

    def cost_card(self, depth: int, batch: int = 0):
        """The captured card for the (depth, B) executable, or None (no
        obs, capture failure, or the compile hasn't happened yet)."""
        return self._cost_cards.get((depth, batch))

    def cost_cards(self) -> list:
        """Snapshot of every captured card (usage endpoint readout)."""
        with self._compile_lock:
            return list(self._cost_cards.values())

    def _get_batched_evolve(self):
        """evolve_batched(grids, steps): vmap of this engine's evolve over
        a stacked leading board axis.  Rebuilt from the CURRENT ``_evolve``
        (the compile fallback may have swapped it) and jitted with the
        input batch donated — the scheduler stacks a fresh buffer per
        coalesced call, so donating it costs nothing and keeps peak HBM at
        one batch, same as the solo path."""
        if self._evolve_batched is None:
            base = self._evolve
            # seam-stitched programs must not donate their input: the
            # band extraction reads the pre-step grid the base step would
            # alias in place, which races on multi-device meshes (see
            # make_seam_stepper) — the hazard vmaps along with the body
            jit_kwargs = {"donate_argnums": 0} if self.donates_input else {}

            @functools.partial(jax.jit, static_argnames=("steps",),
                               **jit_kwargs)
            def evolve_batched(grids, steps: int):
                return jax.vmap(lambda g: base(g, steps))(grids)

            if self.sparse_plan is not None:
                from mpi_tpu.ops import activity
                if activity._cache_optout_active():
                    # the vmapped program embeds the sparse evolve, whose
                    # persistent-cache deserialization corrupts the heap
                    # on jaxlib <= 0.4.37 XLA:CPU — suppress writes so a
                    # same-salt (same-process) rebuild can never read one
                    # back (see activity._CACHE_SALT)
                    evolve_batched = activity._UncachedEvolve(evolve_batched)
            self._evolve_batched = evolve_batched
        return self._evolve_batched

    def compile_segments(self, grid, segments) -> None:
        """Ahead-of-time compile every distinct segment length (compilation
        is "setup"; steady-state stepping is what throughput is measured
        on — same accounting as the reference's topology+alloc phase)."""
        for n in sorted(set(segments)):
            if n > 0:
                self.ensure_compiled(grid, n)

    def step(self, grid, n: int):
        """Advance ``grid`` by ``n`` generations (compiling on first use of
        a new segment length).  The input buffer is donated — callers must
        replace their reference with the returned grid."""
        if n <= 0:
            return grid
        c = self.ensure_compiled(grid, n)
        if self.fault_hook is not None:
            # before the device call: an injected failure must leave the
            # caller's grid untouched (the donation happens inside c)
            self.fault_hook("step")
        self.step_calls += 1
        return c(grid)

    def step_units(self, grid, n: int):
        """Advance ``grid`` by ``n`` generations as n chained depth-1
        dispatches with NO intermediate sync: each link donates the
        previous link's output, so JAX's async dispatch keeps the device
        pipeline full while only ever needing the depth-1 executable —
        the one depth every serve session precompiles.  Callers sync
        (``jax.block_until_ready``) when they need the result; like
        :meth:`step`, the input buffer is donated."""
        for _ in range(max(0, int(n))):
            grid = self.step(grid, 1)
        return grid

    # -- batched stepping (vmapped multi-board serving hot path) ----------

    def batched_sharding(self):
        """Sharding of a stacked ``[B, ...]`` batch: the board axis is
        replicated, each board keeps this engine's (i, j) grid sharding."""
        from jax.sharding import NamedSharding, PartitionSpec

        from mpi_tpu.parallel.mesh import AXES

        return NamedSharding(self.mesh, PartitionSpec(None, *AXES))

    def stack_grids(self, grids):
        """One ``[B, ...]`` device batch from B per-board grids (a single
        fused dispatch, not B copies; jit retraces per batch width).
        Sparse engines stack the whole SparseState pytree leaf-wise
        (single-device by construction, so no out_shardings needed)."""
        import jax.numpy as jnp

        if self._stack_fn is None:
            if self.sparse_plan is not None:
                self._stack_fn = jax.jit(lambda gs: jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *gs))
            else:
                self._stack_fn = jax.jit(
                    lambda gs: jnp.stack(gs),
                    out_shardings=self.batched_sharding()
                )
        return self._stack_fn(list(grids))

    def unstack_grids(self, batched):
        """The B per-board grids of a stacked batch, each back on the
        plain grid sharding (one dispatch with B outputs — the scatter
        half of the scheduler's stack/step/scatter cycle)."""
        from mpi_tpu.parallel.step import grid_sharding

        if self._unstack_fn is None:
            if self.sparse_plan is not None:
                def _unstack(b):
                    B = jax.tree_util.tree_leaves(b)[0].shape[0]
                    return tuple(
                        jax.tree_util.tree_map(lambda x: x[i], b)
                        for i in range(B))

                self._unstack_fn = jax.jit(_unstack)
            else:
                self._unstack_fn = jax.jit(
                    lambda b: tuple(b[i] for i in range(b.shape[0])),
                    out_shardings=grid_sharding(self.mesh),
                )
        return list(self._unstack_fn(batched))

    def init_grids(self, seeds=None, initials=None):
        """A fresh stacked ``[B, ...]`` batch: one board per entry of
        ``seeds`` (hash init) or ``initials`` (checkpoint grids)."""
        if initials is not None:
            boards = [self.init_grid(initial=i) for i in initials]
        else:
            boards = [self.init_grid(seed=s) for s in seeds]
        return self.stack_grids(boards)

    def step_batched(self, grids, n: int):
        """Advance a stacked ``[B, ...]`` batch by ``n`` generations in ONE
        device dispatch (compiling per new ``(n, B)``).  The batch buffer
        is donated — callers must replace their reference with the
        returned batch (per-board grids from :meth:`unstack_grids`)."""
        if n <= 0:
            return grids
        c = self.ensure_compiled_batched(grids, n)
        if self.fault_hook is not None:
            self.fault_hook("batched")
        self.batched_step_calls += 1
        return c(grids)

    def step_batched_units(self, grids, n: int):
        """Batched analog of :meth:`step_units`: n chained depth-1
        batched dispatches, each donating the previous stacked batch,
        with no intermediate sync — the async dispatcher's unit-round
        chain for a batch whose composition holds for n rounds."""
        for _ in range(max(0, int(n))):
            grids = self.step_batched(grids, 1)
        return grids

    def batched_stepper(self, B: int):
        """A ``step(grids, n)`` callable pinned to batch width ``B`` — the
        value the serve layer's batched sub-cache holds per
        ``(plan_signature, B)``; compiled executables still memoize here
        per ``(n, B)``, so a cache hit costs zero new XLA compiles."""
        def step(grids, n):
            got = _batch_width(grids)
            if got != B:
                raise ValueError(
                    f"batched stepper built for B={B}, got {got}")
            return self.step_batched(grids, n)

        step.B = B
        step.engine = self
        return step

    def _get_unpacker(self):
        if self._unpacker is None and self.bitpacked:
            from mpi_tpu.parallel.step import make_sharded_unpacker

            self._unpacker = make_sharded_unpacker(self.mesh)
        return self._unpacker

    def tiles(self, grid):
        """Snapshot tiles ``(pid, tile, r0, c0)`` for every addressable
        shard (the np.asarray fetches inside are the real barrier)."""
        grid = self.raw_grid(grid)
        up = self._get_unpacker()
        return _shard_tiles(up(grid) if up is not None else grid,
                            col_limit=self.col_limit)

    def fetch(self, grid):
        """Final grid as a host numpy array, cropped to the real width
        (None under multi-host execution, where no single host can fetch
        the global array — snapshot tiles are the multi-host output)."""
        if jax.process_count() > 1:
            return None
        final = np.asarray(jax.device_get(self.raw_grid(grid)))
        if self.bitpacked:
            from mpi_tpu.ops.bitlife import unpack_np

            out = unpack_np(final)
            return out[:, : self.config.cols] if self.pad_bits else out
        return final

    def fetch_window(self, grid, r0: int, c0: int, h: int, w: int,
                     shard_timer=None):
        """The host window ``[r0:r0+h, c0:c0+w]`` of the board, fetched
        shard-by-shard: only device shards intersecting the window cross
        the host tunnel (one ``np.asarray`` per intersecting shard),
        never a full-board gather — the serving plane's O(viewport) read
        path.  The window must not wrap (callers decompose a periodic
        wrap into non-wrapping rectangles).  ``shard_timer(dt_s)`` is
        called once per shard transfer when given.  None under
        multi-host execution (same contract as :meth:`fetch`)."""
        import time as _time

        if jax.process_count() > 1:
            return None
        g = self.raw_grid(grid)
        up = self._get_unpacker()
        if up is not None:
            g = up(g)                   # device-side unpack, still sharded
        out = np.zeros((h, w), dtype=np.uint8)
        cl = self.col_limit
        for s in g.addressable_shards:
            sr0 = s.index[0].start or 0
            sc0 = s.index[1].start or 0
            srows, scols = s.data.shape
            if cl is not None:
                scols = min(scols, cl - sc0)
                if scols <= 0:
                    continue            # shard lies entirely in the pad
            ir0, ir1 = max(r0, sr0), min(r0 + h, sr0 + srows)
            ic0, ic1 = max(c0, sc0), min(c0 + w, sc0 + scols)
            if ir0 >= ir1 or ic0 >= ic1:
                continue
            t0 = _time.perf_counter()
            tile = np.asarray(s.data)   # the per-shard transfer barrier
            if shard_timer is not None:
                shard_timer(_time.perf_counter() - t0)
            out[ir0 - r0:ir1 - r0, ic0 - c0:ic1 - c0] = \
                tile[ir0 - sr0:ir1 - sr0, ic0 - sc0:ic1 - sc0]
        return out

    def shard_snapshots(self, grid):
        """``[(r0, c0, tile), ...]`` — every addressable shard's host
        tile in board coordinates (bit columns, pad cropped), the
        per-shard checkpoint payload: each tile is fetched and encoded
        independently, so persistence never holds one full-board
        array."""
        return [(r0, c0, tile) for _pid, tile, r0, c0 in self.tiles(grid)]

    def write_window(self, grid, r0: int, c0: int, patch):
        """A new global grid with ``patch`` written at ``(r0, c0)``:
        only shards intersecting the patch are fetched, edited, and
        re-put; every other shard's device buffer is reused as-is — the
        O(region) half of concurrent disjoint-region edits.  Returns
        None when this engine cannot edit in place (sparse activity
        state, whose dirty map a partial edit would stale; multi-host) —
        the caller falls back to the full re-init path."""
        if jax.process_count() > 1 or self.sparse_plan is not None:
            return None
        g = self.raw_grid(grid)
        patch = np.asarray(patch, dtype=np.uint8)
        h, w = patch.shape
        if self.bitpacked:
            from mpi_tpu.ops.bitlife import WORD, pack_np, unpack_np
        arrays = []
        for s in g.addressable_shards:
            sr0 = s.index[0].start or 0
            sc0 = s.index[1].start or 0
            srows = s.data.shape[0]
            if self.bitpacked:
                sc0 *= WORD
                scols = s.data.shape[1] * WORD
            else:
                scols = s.data.shape[1]
            ir0, ir1 = max(r0, sr0), min(r0 + h, sr0 + srows)
            ic0, ic1 = max(c0, sc0), min(c0 + w, sc0 + scols)
            if ir0 >= ir1 or ic0 >= ic1:
                arrays.append(s.data)   # untouched: reuse device buffer
                continue
            if self.bitpacked:
                bits = unpack_np(np.asarray(s.data))
            else:
                bits = np.array(np.asarray(s.data), dtype=np.uint8,
                                copy=True)
            bits[ir0 - sr0:ir1 - sr0, ic0 - sc0:ic1 - sc0] = \
                patch[ir0 - r0:ir1 - r0, ic0 - c0:ic1 - c0]
            if self.bitpacked:
                bits = pack_np(bits)
            arrays.append(jax.device_put(bits, s.device))
        return jax.make_array_from_single_device_arrays(
            g.shape, g.sharding, arrays)

    def population(self, grid) -> int:
        """Live-cell count without fetching the whole grid (a rows-long
        vector crosses the host tunnel, not rows x cols cells).  Exact on
        padded runs too: the steppers re-kill the dead pad every
        generation, so packed popcounts never see pad bits."""
        import jax.numpy as jnp
        from jax import lax

        grid = self.raw_grid(grid)
        if self.bitpacked:
            per_row = jnp.sum(
                lax.population_count(grid).astype(jnp.uint32), axis=1)
        else:
            per_row = jnp.sum(grid.astype(jnp.uint32), axis=1)
        return int(np.asarray(jax.device_get(per_row), dtype=np.int64).sum())

    def population_batched(self, grids) -> List[int]:
        """Per-board live-cell counts of a stacked batch — one device
        reduction to a ``[B, rows]`` vector, host-summed in int64 (the
        same overflow discipline as :meth:`population`)."""
        import jax.numpy as jnp
        from jax import lax

        grids = self.raw_grid(grids)
        if self.bitpacked:
            per_row = jnp.sum(
                lax.population_count(grids).astype(jnp.uint32), axis=2)
        else:
            per_row = jnp.sum(grids.astype(jnp.uint32), axis=2)
        host = np.asarray(jax.device_get(per_row), dtype=np.int64)
        return [int(v) for v in host.sum(axis=1)]

    def fetch_batched(self, grids) -> Optional[List[np.ndarray]]:
        """Per-board host numpy arrays of a stacked batch, each cropped to
        the real width (None under multi-host execution — same contract
        as :meth:`fetch`)."""
        if jax.process_count() > 1:
            return None
        final = np.asarray(jax.device_get(self.raw_grid(grids)))
        if self.bitpacked:
            from mpi_tpu.ops.bitlife import unpack_np

            boards = [unpack_np(b) for b in final]
            if self.pad_bits:
                boards = [b[:, : self.config.cols] for b in boards]
            return boards
        return [np.asarray(b) for b in final]


def build_engine(config: GolConfig, mesh=None, depths=None, tune=None,
                 blocks=None) -> Engine:
    """Resolve the full plan for ``config`` — mesh, pad-to-32 width,
    engine dispatch, seam wrapping, overlap feasibility — and return an
    :class:`Engine` holding the (uncompiled) stepper.

    This is the stable seam the serve layer's EngineCache memoizes behind
    ``mpi_tpu.config.plan_signature``; ``run_tpu`` calls it once per
    invocation, the serve layer once per cache miss.  Planning notes print
    to stderr as they are decided (same wording/ordering as before the
    refactor) and are also retained on ``Engine.notes`` for /stats.

    ``depths``: the local-step depths that will actually be traced
    (``run_tpu`` passes the exact segment plan via ``segment_depths``);
    None uses the conservative 1..comm_every superset — right for
    persistent engines, which step by arbitrary k.

    ``tune``: an opt-in :class:`~mpi_tpu.tune.TuneCache` (or path) — a
    persisted autotuner winner for this exact (platform, requested
    plan) replaces the requested knobs before planning; the default
    ``None`` never reads the cache, so untuned builds are byte-for-byte
    the pre-tuner program.  ``blocks`` force-overrides the fused SWAR
    kernel's (BM, CM) block pick (the tuner probes candidates with it;
    a cached winner's ``blocks`` entry arrives through ``tune``)."""
    import sys

    mesh = mesh if mesh is not None else make_mesh(config.mesh_shape)
    from mpi_tpu.config import ConfigError, validate_mesh
    from mpi_tpu.parallel.mesh import AXES

    mi, mj = mesh.shape[AXES[0]], mesh.shape[AXES[1]]
    tuned_plan = None
    if tune is not None:
        from mpi_tpu.tune import resolve_tuned

        config, tuned_plan = resolve_tuned(config, (mi, mj), tune)
        if tuned_plan is not None and blocks is None:
            blocks = tuned_plan.get("blocks")
    # Auto-chosen meshes must pass the same compatibility checks as
    # explicit --mesh shapes (fail fast, not deep in shard_map).
    validate_mesh(
        config.rows, config.cols, (mi, mj),
        config.rule.radius * config.comm_every,
    )

    notes = []

    def _note(msg: str) -> None:
        notes.append(msg)
        print(f"note: {msg}", file=sys.stderr)

    # Engine choice: bitpacked SWAR (32 cells/lane) for radius-1 rules when
    # every shard's width packs into whole uint32 words; dense uint8 else.
    # Non-word-aligned dead-boundary widths are padded to alignment and
    # still take the packed engines (pad-to-32 routing, VERDICT r3 item
    # 3): the steppers re-kill the dead pad every generation and the
    # outputs crop back to the real width.
    from mpi_tpu.ops.bitlife import WORD

    cols_eff, pad_bits = plan_pad_width(config, mj,
                                        shard_rows=config.rows // mi)
    packed_mode = config.rule.radius == 1 and (cols_eff // mj) % WORD == 0
    if depths is None:
        depths = range(1, config.comm_every + 1)  # conservative superset
    # radius > 1: the packed bit-sliced LtL engine replaces the dense path
    # when it applies (same packed init/snapshot plumbing) — the fused
    # Pallas kernel on one device, the shard_map/ppermute XLA stepper on
    # meshes (with stitched-band overlap when requested)
    ltl_mode, ltl_note = (None, None) if packed_mode \
        else select_ltl_mode(config, mi, mj, cols=cols_eff, pad_bits=pad_bits)
    if not packed_mode and not ltl_mode:
        cols_eff, pad_bits = config.cols, 0  # dense path: no padding
        if (config.rule.radius == 1 and config.boundary == "periodic"
                and (config.cols // mj) % WORD != 0):
            # radius-1 misaligned landing on dense means the periodic
            # seam gate declined (gated on the boundary itself, ADVICE
            # r5 — dead boundaries always pad, so only periodic can land
            # here) — same note discipline as the radius>1 fallbacks: a
            # run on the ~6-25x slower engine must say why (most
            # misaligned widths ride the packed engines since round 5)
            _note(
                f"non-word-aligned periodic width {config.cols}"
                f"/{mj} cols per shard: dense engine (seam stitching "
                f"needs comm_every*radius <= 31 and width >= "
                f"{4 * config.comm_every * config.rule.radius})"
            )
    # periodic + pad: the packed stepper runs with dead-wrap seam
    # semantics and the seam wrapper recomputes/stitches the wrap
    # columns (parallel/seam.py, VERDICT r4 item 5).  One wrapper
    # helper so the main path and the compile-fallback path cannot
    # drift in arguments.
    seam = pad_bits > 0 and config.boundary == "periodic"

    def _wrap_seam(ev):
        if not seam:
            return ev
        from mpi_tpu.parallel.seam import make_seam_stepper

        return make_seam_stepper(
            ev, config.rule, config.cols, config.comm_every
        )
    if ltl_note is not None:
        _note(ltl_note)
    if config.overlap and pad_bits and config.comm_every > 1 \
            and (packed_mode or ltl_mode == "sharded"):
        # padded widths at K > 1 run the exchange-all body (the pad must
        # be re-killed between generations) — say so instead of silently
        # dropping the requested overlap
        _note(
            "--overlap dropped: padded (non-word-aligned) width "
            "with comm_every > 1 uses the exchange-all packed body "
            "(still far faster than the dense engine; overlap needs "
            "comm_every 1 here)"
        )
    overlap_eff = config.overlap
    if config.overlap and mi * mj > 1 \
            and not (pad_bits and config.comm_every > 1):
        # fail fast instead of silently running without the requested
        # overlap: tiles must be big enough for the stitched edge bands
        # (judged on the effective — padded — geometry).  Padded K>1 runs
        # already dropped the overlap above — no bands will be built, so
        # the band-size check must not reject them.  On AUTO-padded
        # geometry (pad_bits > 0) a too-small tile drops the overlap
        # with a note instead: the user never chose the padded shape, so
        # a hard error on a config that ran in round 4 (dense engine)
        # would be a regression — the packed run without overlap is
        # still far faster than the dense run with it.
        def _overlap_too_small(need_msg):
            nonlocal overlap_eff
            if pad_bits:
                _note(
                    f"--overlap dropped: padded tile too small for "
                    f"the stitched bands ({need_msg}); running the packed "
                    f"engine without overlap"
                )
                overlap_eff = False
            else:
                raise ConfigError(f"--overlap needs {need_msg}")

        tile_r, tile_c = config.rows // mi, cols_eff // mj
        if packed_mode:
            if tile_r < 2 * config.comm_every or tile_c < 2 * WORD:
                _overlap_too_small(
                    f"tiles >= {2 * config.comm_every} rows x {2 * WORD} "
                    f"cols (got {tile_r}x{tile_c})"
                )
        elif ltl_mode == "sharded":
            d = config.comm_every * config.rule.radius
            if tile_r < 2 * d or tile_c < 2 * WORD:
                _overlap_too_small(
                    f"tiles >= {2 * d} rows x {2 * WORD} cols for the "
                    f"bit-sliced radius-{config.rule.radius} bands "
                    f"(got {tile_r}x{tile_c})"
                )
        else:
            d = 2 * config.comm_every * config.rule.radius
            if min(tile_r, tile_c) < d:
                raise ConfigError(
                    f"--overlap needs tiles >= {d}x{d} for radius "
                    f"{config.rule.radius} x comm_every {config.comm_every} "
                    f"bands (got {tile_r}x{tile_c})"
                )
    if packed_mode or ltl_mode:
        if ltl_mode == "pallas":
            from mpi_tpu.ops.pallas_bitltl import make_pallas_ltl_stepper

            _, interpret = _pallas_single_device_mode()
            evolve = make_pallas_ltl_stepper(
                config.rule, config.boundary, interpret=interpret,
                gens=config.comm_every,
            )
            used_pallas = True
        elif ltl_mode == "sharded":
            from mpi_tpu.parallel.step import (
                ltl_local_pallas_ok, make_sharded_ltl_stepper,
            )

            use, interpret = _pallas_single_device_mode()
            evolve = make_sharded_ltl_stepper(
                mesh, config.rule, config.boundary,
                gens_per_exchange=config.comm_every, overlap=overlap_eff,
                use_pallas=use, pallas_interpret=interpret, pad_bits=pad_bits,
                seam_pad=seam,
            )
            shard = _shard_shape_packed(config, mesh, cols_eff)
            dep = ([k for k in depths if k == 1] if pad_bits else depths)
            used_pallas = use and any(
                ltl_local_pallas_ok(shard, config.rule, k) for k in dep
            )
        else:
            evolve, used_pallas = _pick_packed_evolve(
                config, mesh, mi * mj, cols=cols_eff, pad_bits=pad_bits,
                depths=depths, seam_pad=seam, overlap=overlap_eff,
                blocks=blocks,
            )
    else:
        evolve, used_pallas = _pick_dense_evolve(
            config, mesh, mi * mj, depths=depths, blocks=blocks,
        )
    evolve = _wrap_seam(evolve)

    def fallback_factory():
        # the always-available shard_map/XLA stepper, for a fused Pallas
        # kernel that fails to compile (same arguments as the main path —
        # the one _wrap_seam helper keeps them from drifting)
        from mpi_tpu.parallel.step import (
            make_sharded_bit_stepper, make_sharded_ltl_stepper,
        )

        if packed_mode:
            ev = make_sharded_bit_stepper(
                mesh, config.rule, config.boundary,
                gens_per_exchange=config.comm_every, overlap=overlap_eff,
                pad_bits=pad_bits, seam_pad=seam,
            )
        elif ltl_mode:
            # comm_every·r ≤ max_gens(r)·r ≤ 8·1 | 4·2 | 2·4 ≤ 8 word
            # halo bits — always within the sharded stepper's 31-bit bound
            ev = make_sharded_ltl_stepper(
                mesh, config.rule, config.boundary,
                gens_per_exchange=config.comm_every, overlap=overlap_eff,
                pad_bits=pad_bits, seam_pad=seam,
            )
        else:
            ev = make_sharded_stepper(
                mesh, config.rule, config.boundary,
                gens_per_exchange=config.comm_every, overlap=config.overlap,
            )
        return _wrap_seam(ev)

    # Activity-gated sparse stepping (ops/activity.py): wrap whichever
    # evolve won the dispatch above in the dirty-tile gate.  The wrapper
    # is engine-agnostic — it needs the base evolve (its dense branch),
    # a tile-local step (haloed block -> stepped interior) and the tile
    # geometry; everything downstream (segment tables, batching, seam of
    # the compile fallback) sees one ordinary evolve over a SparseState.
    sparse_plan = None
    if config.sparse_tile:
        from mpi_tpu.ops import activity

        T = config.sparse_tile
        bitp = packed_mode or bool(ltl_mode)
        if mi * mj != 1:
            raise ConfigError(
                f"sparse_tile requires a single-device mesh (got "
                f"{mi}x{mj}); shard OR activity-gate, not both yet")
        if bitp and T % WORD != 0:
            raise ConfigError(
                f"sparse_tile {T} must be a multiple of {WORD} on the "
                f"packed engines (tiles are expressed in words); use a "
                f"multiple of {WORD} or a rule/width that takes the "
                f"dense engine")
        if pad_bits:
            raise ConfigError(
                f"sparse_tile on a pad-to-32 width ({config.cols} cols) "
                f"is unsupported; use a word-aligned width")
        if packed_mode:
            from mpi_tpu.ops.bitlife import bit_step as _local_full
        elif ltl_mode:
            from mpi_tpu.ops.bitltl import ltl_step as _local_full
        else:
            from mpi_tpu.ops.stencil import step as _local_full
        sparse_plan = activity.make_plan(
            rows=config.rows,
            cols_units=(cols_eff // WORD) if bitp else config.cols,
            tile_px=T, radius=config.rule.radius,
            periodic=(config.boundary == "periodic"), packed=bitp,
        )
        def sparse_local(strip):
            # one dead-boundary kernel call over the stacked haloed tiles;
            # activity.py slices the interiors out (cross-tile bleed in
            # the strip only reaches halo rows, which it discards)
            return _local_full(strip, config.rule, "dead")

        evolve = activity.make_sparse_evolve(evolve, sparse_local,
                                             sparse_plan)
        _base_fallback = fallback_factory

        def fallback_factory():
            return activity.make_sparse_evolve(
                _base_fallback(), sparse_local, sparse_plan)

    if tuned_plan is not None:
        _note(f"autotuned plan applied: {tuned_plan} "
              f"(tune cache winner for this signature)")
    engine = Engine(
        config, mesh, evolve, bitpacked=packed_mode or bool(ltl_mode),
        cols_eff=cols_eff, pad_bits=pad_bits, used_pallas=used_pallas,
        fallback_factory=fallback_factory, notes=notes,
        sparse_plan=sparse_plan,
    )
    engine.tuned_plan = tuned_plan
    return engine


def run_tpu(
    config: GolConfig,
    timer: Optional[PhaseTimer] = None,
    snapshot_cb: Optional[SnapshotCb] = None,
    mesh=None,
    initial=None,
    start_iteration: int = 0,
):
    """Run one configuration; returns the final grid as a host numpy array
    (or None under multi-host execution, where no single host can fetch
    the global array — the snapshot tiles are the multi-host output).

    initial/start_iteration support checkpoint-restart: pass a grid loaded
    by ``golio.load_snapshot`` (or, multihost, a region loader backed by
    ``golio.assemble_region``) and the iteration it was saved at.

    One-shot wrapper over :func:`build_engine`: plan + compile is "setup"
    (the reference's topology+alloc phase), the segment loop is the timed
    steady state — identical CLI contract, snapshot files, and stderr
    notes as before the engine refactor.
    """
    timer = timer or PhaseTimer()
    # the segment plan (and so the set of stepper depths that will be
    # traced) is known up front — the Pallas compile-fallback gate is
    # computed from the depths that actually run
    want_snapshots = snapshot_cb is not None and config.snapshot_every > 0
    segments = plan_segments(
        config.steps, config.snapshot_every if want_snapshots else 0)
    engine = build_engine(
        config, mesh=mesh, depths=segment_depths(segments, config.comm_every))
    grid = engine.init_grid(initial=initial)
    engine.compile_segments(grid, segments)

    from mpi_tpu.utils.platform import force_fetch

    # Timed regions must close with a real fetch, not block_until_ready
    # (see force_fetch); the warm call here also compiles the tiny slice
    # executables inside the setup-timed phase.  (raw_grid: a sparse
    # engine's state is a pytree, force_fetch wants the array's shards.)
    force_fetch(engine.raw_grid(grid))
    timer.setup_done()

    it = start_iteration
    if want_snapshots and it == 0:
        snapshot_cb(0, engine.tiles(grid))
    for n in segments:
        grid = engine.step(grid, n)
        it += n
        if want_snapshots:
            # tiles' np.asarray(shard.data) fetches are the real barrier
            # here; no block_until_ready needed (or trusted)
            snapshot_cb(it, engine.tiles(grid))
    force_fetch(engine.raw_grid(grid))
    timer.finish()
    return engine.fetch(grid)


def device_count() -> int:
    return len(jax.devices())
