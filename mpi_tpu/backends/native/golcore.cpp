// golcore — native C++ engine for mpi_tpu.
//
// The reference implements its native layer with MPI (main.cpp) and a serial
// C++ oracle (main_serial.cpp).  This is the framework's equivalent, built
// from scratch:
//
//   * gol_init            — the decomposition-invariant hash init, bit-identical
//                           to utils/hashinit.py (replaces srand(rank)/srand(seed),
//                           reference main.cpp:70 / main_serial.cpp:36).
//   * gol_step/gol_evolve — serial engine: separable window-sum neighbor counts
//                           + rule-table apply, double buffered (the corrected,
//                           generalized form of main_serial.cpp:45-71; boundary
//                           is a flag instead of hardcoded periodic).
//   * gol_evolve_par      — multi-worker engine: 2D tile decomposition over a
//                           worker mesh, each tile owning a radius-wide ghost
//                           ring filled by an explicit 8-neighbor halo exchange
//                           with barrier phases — the shared-memory analog of
//                           the reference's MPI_Isend/Irecv distr_borders
//                           (main.cpp:36-65), with the halo pairing bug fixed
//                           (ghosts hold the geometrically adjacent neighbor's
//                           edge, SURVEY.md §5.8 quirk #1).
//
// Exposed via a C ABI for the ctypes wrapper in backends/cpp.py.

#include <cstdint>
#include <cstring>
#include <condition_variable>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Hash init — must match utils/hashinit.py exactly (pinned by tests).
// murmur3 32-bit finalizer; keys folded in with odd multiplicative constants.
// ---------------------------------------------------------------------------

inline uint32_t fmix32(uint32_t h) {
    h ^= h >> 16;
    h *= 0x85EBCA6Bu;
    h ^= h >> 13;
    h *= 0xC2B2AE35u;
    h ^= h >> 16;
    return h;
}

inline uint32_t cell_hash(uint32_t seed, uint32_t i, uint32_t j) {
    uint32_t hi = fmix32(seed ^ (i * 0x9E3779B1u));
    return fmix32(hi ^ (j * 0x85EBCA77u));
}

// ---------------------------------------------------------------------------
// Stencil on a padded tile.
//
// buf: (rows + 2r) x (cols + 2r), row-major, ghost ring included.
// Separable counts: vertical window sum into a rowsum scratch (kept at full
// padded width so the horizontal pass sees shifted columns), then horizontal
// window sum minus the center — same algorithm as ops/stencil.py, O(2r+1)
// adds per cell per axis instead of (2r+1)^2 gathers.
// ---------------------------------------------------------------------------

struct RuleTables {
    const uint8_t* birth;    // indexed by neighbor count
    const uint8_t* survive;
    int radius;
};

void step_padded(const uint8_t* in, uint8_t* out, int64_t rows, int64_t cols,
                 const RuleTables& rule, uint8_t* rowsum /* rows x (cols+2r) */) {
    const int r = rule.radius;
    const int win = 2 * r + 1;
    const int64_t pw = cols + 2 * r;  // padded width
    for (int64_t i = 0; i < rows; ++i) {
        const uint8_t* base = in + i * pw;
        uint8_t* rs = rowsum + i * pw;
        for (int64_t j = 0; j < pw; ++j) rs[j] = base[j];
        for (int k = 1; k < win; ++k) {
            const uint8_t* row = in + (i + k) * pw;
            for (int64_t j = 0; j < pw; ++j) rs[j] += row[j];
        }
    }
    for (int64_t i = 0; i < rows; ++i) {
        const uint8_t* rs = rowsum + i * pw;
        const uint8_t* center_row = in + (i + r) * pw + r;
        uint8_t* dst = out + (i + r) * pw + r;
        for (int64_t j = 0; j < cols; ++j) {
            uint8_t c = rs[j];
            for (int k = 1; k < win; ++k) c += rs[j + k];
            c -= center_row[j];
            dst[j] = center_row[j] ? rule.survive[c] : rule.birth[c];
        }
    }
}

// Fill the ghost ring of a standalone padded buffer from its own interior
// (periodic) or zeros (dead).  Used by the serial engine.
void fill_ghosts_self(uint8_t* buf, int64_t rows, int64_t cols, int r, bool periodic) {
    const int64_t pw = cols + 2 * r;
    const int64_t ph = rows + 2 * r;
    if (!periodic) {
        for (int64_t i = 0; i < ph; ++i) {
            uint8_t* row = buf + i * pw;
            if (i < r || i >= rows + r) {
                std::memset(row, 0, pw);
            } else {
                std::memset(row, 0, r);
                std::memset(row + cols + r, 0, r);
            }
        }
        return;
    }
    // periodic: wrap rows then columns (row pass first so column wrap copies
    // the already-wrapped rows — corners come out right).
    for (int k = 0; k < r; ++k) {
        std::memcpy(buf + k * pw + r, buf + (rows + k) * pw + r, cols);
        std::memcpy(buf + (rows + r + k) * pw + r, buf + (r + k) * pw + r, cols);
    }
    for (int64_t i = 0; i < ph; ++i) {
        uint8_t* row = buf + i * pw;
        for (int k = 0; k < r; ++k) {
            row[k] = row[cols + k];
            row[cols + r + k] = row[r + k];
        }
    }
}

// ---------------------------------------------------------------------------
// Reusable spinning-free barrier (C++17; std::barrier is C++20).
// ---------------------------------------------------------------------------

class Barrier {
  public:
    explicit Barrier(int n) : n_(n), waiting_(0), phase_(0) {}
    void arrive_and_wait() {
        std::unique_lock<std::mutex> lk(m_);
        int phase = phase_;
        if (++waiting_ == n_) {
            waiting_ = 0;
            ++phase_;
            cv_.notify_all();
        } else {
            cv_.wait(lk, [&] { return phase_ != phase; });
        }
    }

  private:
    int n_, waiting_, phase_;
    std::mutex m_;
    std::condition_variable cv_;
};

// ---------------------------------------------------------------------------
// Parallel engine: tile mesh + ghost-ring halo exchange.
// ---------------------------------------------------------------------------

struct Tile {
    int64_t r0, c0, rows, cols;  // interior placement in the global grid
    std::vector<uint8_t> a, b;   // double-buffered padded storage
    std::vector<uint8_t> rowsum;
};

struct ParEngine {
    int ti, tj, radius;
    bool periodic;
    std::vector<Tile> tiles;

    Tile& at(int i, int j) { return tiles[(size_t)i * tj + j]; }

    // Neighbor tile index along one axis, honoring boundary; -1 = none (dead).
    int wrap(int x, int n) const {
        if (x >= 0 && x < n) return x;
        return periodic ? (x + n) % n : -1;
    }
};

// Copy a rect from src tile's CURRENT interior into dst tile's padded buffer.
// Coordinates are interior-relative (0-based); dst offsets are padded-buffer
// absolute.  cur selects which double buffer is "current" this step.
inline void copy_rect(const Tile& src, const std::vector<uint8_t>& src_buf, int r,
                      int64_t si, int64_t sj, Tile& dst, std::vector<uint8_t>& dst_buf,
                      int64_t di, int64_t dj, int64_t h, int64_t w) {
    const int64_t spw = src.cols + 2 * r;
    const int64_t dpw = dst.cols + 2 * r;
    for (int64_t k = 0; k < h; ++k) {
        std::memcpy(dst_buf.data() + (di + k) * dpw + dj,
                    src_buf.data() + (si + r + k) * spw + sj + r, w);
    }
}

// Fill every ghost slab of tile (i, j) from its 8 mesh neighbors' interiors —
// the shared-memory distr_borders.  Reads neighbors' current buffers (stable
// during the exchange phase; a barrier separates exchange from compute).
void exchange_tile(ParEngine& e, int i, int j, bool cur_is_a) {
    Tile& t = e.at(i, j);
    std::vector<uint8_t>& dst = cur_is_a ? t.a : t.b;
    const int r = e.radius;
    const int64_t pw = t.cols + 2 * r;

    for (int di = -1; di <= 1; ++di) {
        for (int dj = -1; dj <= 1; ++dj) {
            if (di == 0 && dj == 0) continue;
            // Destination slab in t's padded buffer.
            int64_t dst_i = di < 0 ? 0 : (di == 0 ? r : t.rows + r);
            int64_t dst_j = dj < 0 ? 0 : (dj == 0 ? r : t.cols + r);
            int64_t h = di == 0 ? t.rows : r;
            int64_t w = dj == 0 ? t.cols : r;
            int ni = e.wrap(i + di, e.ti);
            int nj = e.wrap(j + dj, e.tj);
            if (ni < 0 || nj < 0) {
                for (int64_t k = 0; k < h; ++k)
                    std::memset(dst.data() + (dst_i + k) * pw + dst_j, 0, w);
                continue;
            }
            Tile& s = e.at(ni, nj);
            const std::vector<uint8_t>& src = cur_is_a ? s.a : s.b;
            // Source rect: the neighbor's interior edge facing us.
            int64_t si = di < 0 ? s.rows - r : 0;  // coming from above: its bottom
            int64_t sj = dj < 0 ? s.cols - r : 0;
            copy_rect(s, src, r, si, sj, t, dst, dst_i, dst_j, h, w);
        }
    }
}

}  // namespace

extern "C" {

// Fill a (rows x cols) uint8 tile of the global grid starting at
// (row_off, col_off); alive iff hash % 3 == 0 (P = 1/3, matching the
// reference's rand() % 3 == 0 density, main.cpp:69-73).
void gol_init(uint8_t* grid, int64_t rows, int64_t cols, uint32_t seed,
              int64_t row_off, int64_t col_off) {
    for (int64_t i = 0; i < rows; ++i) {
        uint32_t gi = (uint32_t)(row_off + i);
        for (int64_t j = 0; j < cols; ++j) {
            uint32_t gj = (uint32_t)(col_off + j);
            grid[i * cols + j] = cell_hash(seed, gi, gj) % 3u == 0u;
        }
    }
}

// One serial step: in/out are UNPADDED (rows x cols) buffers.
void gol_step(const uint8_t* in, uint8_t* out, int64_t rows, int64_t cols,
              const uint8_t* birth_table, const uint8_t* survive_table,
              int radius, int periodic) {
    const int r = radius;
    const int64_t pw = cols + 2 * r, ph = rows + 2 * r;
    std::vector<uint8_t> pin((size_t)(ph * pw)), pout((size_t)(ph * pw));
    std::vector<uint8_t> rowsum((size_t)(rows * pw));
    for (int64_t i = 0; i < rows; ++i)
        std::memcpy(pin.data() + (i + r) * pw + r, in + i * cols, cols);
    fill_ghosts_self(pin.data(), rows, cols, r, periodic != 0);
    RuleTables rule{birth_table, survive_table, r};
    step_padded(pin.data(), pout.data(), rows, cols, rule, rowsum.data());
    for (int64_t i = 0; i < rows; ++i)
        std::memcpy(out + i * cols, pout.data() + (i + r) * pw + r, cols);
}

// Serial evolution, double buffered in padded space; result lands in grid.
void gol_evolve(uint8_t* grid, int64_t rows, int64_t cols, int64_t steps,
                const uint8_t* birth_table, const uint8_t* survive_table,
                int radius, int periodic) {
    const int r = radius;
    const int64_t pw = cols + 2 * r, ph = rows + 2 * r;
    std::vector<uint8_t> a((size_t)(ph * pw)), b((size_t)(ph * pw));
    std::vector<uint8_t> rowsum((size_t)(rows * pw));
    for (int64_t i = 0; i < rows; ++i)
        std::memcpy(a.data() + (i + r) * pw + r, grid + i * cols, cols);
    RuleTables rule{birth_table, survive_table, r};
    uint8_t *cur = a.data(), *nxt = b.data();
    for (int64_t s = 0; s < steps; ++s) {
        fill_ghosts_self(cur, rows, cols, r, periodic != 0);
        step_padded(cur, nxt, rows, cols, rule, rowsum.data());
        std::swap(cur, nxt);
    }
    for (int64_t i = 0; i < rows; ++i)
        std::memcpy(grid + i * cols, cur + (i + r) * pw + r, cols);
}

// Parallel evolution over a ti x tj worker-tile mesh (one thread per tile).
// Requires rows % ti == 0 and cols % tj == 0; returns 0 on success.
int gol_evolve_par(uint8_t* grid, int64_t rows, int64_t cols, int64_t steps,
                   const uint8_t* birth_table, const uint8_t* survive_table,
                   int radius, int periodic, int ti, int tj) {
    if (ti < 1 || tj < 1 || rows % ti || cols % tj) return 1;
    const int r = radius;
    const int64_t trows = rows / ti, tcols = cols / tj;
    if (trows < r || tcols < r) return 2;  // ghost slab must fit in one neighbor

    ParEngine e;
    e.ti = ti; e.tj = tj; e.radius = r; e.periodic = periodic != 0;
    e.tiles.resize((size_t)ti * tj);
    const int64_t pw = tcols + 2 * r, ph = trows + 2 * r;
    for (int i = 0; i < ti; ++i) {
        for (int j = 0; j < tj; ++j) {
            Tile& t = e.at(i, j);
            t.r0 = i * trows; t.c0 = j * tcols; t.rows = trows; t.cols = tcols;
            t.a.assign((size_t)(ph * pw), 0);
            t.b.assign((size_t)(ph * pw), 0);
            t.rowsum.assign((size_t)(trows * pw), 0);
            for (int64_t k = 0; k < trows; ++k)
                std::memcpy(t.a.data() + (k + r) * pw + r,
                            grid + (t.r0 + k) * cols + t.c0, tcols);
        }
    }

    Barrier barrier(ti * tj);
    std::vector<std::thread> workers;
    workers.reserve((size_t)ti * tj);
    for (int i = 0; i < ti; ++i) {
        for (int j = 0; j < tj; ++j) {
            workers.emplace_back([&e, &barrier, i, j, steps, birth_table,
                                  survive_table]() {
                Tile& t = e.at(i, j);
                RuleTables rule{birth_table, survive_table, e.radius};
                bool cur_is_a = true;
                for (int64_t s = 0; s < steps; ++s) {
                    exchange_tile(e, i, j, cur_is_a);
                    barrier.arrive_and_wait();  // all ghosts filled
                    uint8_t* cur = cur_is_a ? t.a.data() : t.b.data();
                    uint8_t* nxt = cur_is_a ? t.b.data() : t.a.data();
                    step_padded(cur, nxt, t.rows, t.cols, rule, t.rowsum.data());
                    cur_is_a = !cur_is_a;
                    barrier.arrive_and_wait();  // all interiors written
                }
            });
        }
    }
    for (auto& w : workers) w.join();

    const bool final_is_a = (steps % 2) == 0;
    for (int i = 0; i < ti; ++i) {
        for (int j = 0; j < tj; ++j) {
            Tile& t = e.at(i, j);
            const uint8_t* buf = final_is_a ? t.a.data() : t.b.data();
            for (int64_t k = 0; k < trows; ++k)
                std::memcpy(grid + (t.r0 + k) * cols + t.c0,
                            buf + (k + r) * pw + r, tcols);
        }
    }
    return 0;
}

}  // extern "C"
