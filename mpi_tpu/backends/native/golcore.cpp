// golcore — native C++ engine for mpi_tpu.
//
// The reference implements its native layer with MPI (main.cpp) and a serial
// C++ oracle (main_serial.cpp).  This is the framework's equivalent, built
// from scratch:
//
//   * gol_init            — the decomposition-invariant hash init, bit-identical
//                           to utils/hashinit.py (replaces srand(rank)/srand(seed),
//                           reference main.cpp:70 / main_serial.cpp:36).
//   * gol_step/gol_evolve — serial engine: separable window-sum neighbor counts
//                           + rule-table apply, double buffered (the corrected,
//                           generalized form of main_serial.cpp:45-71; boundary
//                           is a flag instead of hardcoded periodic).
//   * gol_evolve_par      — multi-worker engine: 2D tile decomposition over a
//                           worker mesh, each tile owning a radius-wide ghost
//                           ring filled by an explicit 8-neighbor halo exchange
//                           with barrier phases — the shared-memory analog of
//                           the reference's MPI_Isend/Irecv distr_borders
//                           (main.cpp:36-65), with the halo pairing bug fixed
//                           (ghosts hold the geometrically adjacent neighbor's
//                           edge, SURVEY.md §5.8 quirk #1).
//
// Exposed via a C ABI for the ctypes wrapper in backends/cpp.py.

#include <chrono>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <condition_variable>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Hash init — must match utils/hashinit.py exactly (pinned by tests).
// murmur3 32-bit finalizer; keys folded in with odd multiplicative constants.
// ---------------------------------------------------------------------------

inline uint32_t fmix32(uint32_t h) {
    h ^= h >> 16;
    h *= 0x85EBCA6Bu;
    h ^= h >> 13;
    h *= 0xC2B2AE35u;
    h ^= h >> 16;
    return h;
}

inline uint32_t cell_hash(uint32_t seed, uint32_t i, uint32_t j) {
    uint32_t hi = fmix32(seed ^ (i * 0x9E3779B1u));
    return fmix32(hi ^ (j * 0x85EBCA77u));
}

// ---------------------------------------------------------------------------
// Stencil on a padded tile.
//
// buf: (rows + 2r) x (cols + 2r), row-major, ghost ring included.
// Separable counts: vertical window sum into a rowsum scratch (kept at full
// padded width so the horizontal pass sees shifted columns), then horizontal
// window sum minus the center — same algorithm as ops/stencil.py, O(2r+1)
// adds per cell per axis instead of (2r+1)^2 gathers.
// ---------------------------------------------------------------------------

struct RuleTables {
    const uint8_t* birth;    // indexed by neighbor count
    const uint8_t* survive;
    int radius;
};

void step_padded(const uint8_t* in, uint8_t* out, int64_t rows, int64_t cols,
                 const RuleTables& rule, uint8_t* rowsum /* rows x (cols+2r) */) {
    const int r = rule.radius;
    const int win = 2 * r + 1;
    const int64_t pw = cols + 2 * r;  // padded width
    for (int64_t i = 0; i < rows; ++i) {
        const uint8_t* base = in + i * pw;
        uint8_t* rs = rowsum + i * pw;
        for (int64_t j = 0; j < pw; ++j) rs[j] = base[j];
        for (int k = 1; k < win; ++k) {
            const uint8_t* row = in + (i + k) * pw;
            for (int64_t j = 0; j < pw; ++j) rs[j] += row[j];
        }
    }
    for (int64_t i = 0; i < rows; ++i) {
        const uint8_t* rs = rowsum + i * pw;
        const uint8_t* center_row = in + (i + r) * pw + r;
        uint8_t* dst = out + (i + r) * pw + r;
        for (int64_t j = 0; j < cols; ++j) {
            uint8_t c = rs[j];
            for (int k = 1; k < win; ++k) c += rs[j + k];
            c -= center_row[j];
            dst[j] = center_row[j] ? rule.survive[c] : rule.birth[c];
        }
    }
}

// ---------------------------------------------------------------------------
// Reusable spinning-free barrier (C++17; std::barrier is C++20).
// ---------------------------------------------------------------------------

class Barrier {
  public:
    explicit Barrier(int n) : n_(n), waiting_(0), phase_(0) {}
    void arrive_and_wait() {
        std::unique_lock<std::mutex> lk(m_);
        int phase = phase_;
        if (++waiting_ == n_) {
            waiting_ = 0;
            ++phase_;
            cv_.notify_all();
        } else {
            cv_.wait(lk, [&] { return phase_ != phase; });
        }
    }

  private:
    int n_, waiting_, phase_;
    std::mutex m_;
    std::condition_variable cv_;
};

// ---------------------------------------------------------------------------
// Bitpacked SWAR engine (radius-1 rules, cols % 64 == 0) — the native
// mirror of the TPU backend's ops/bitlife.py design: 64 cells per uint64,
// neighbor counts as bit-sliced carry-save sums, any outer-totalistic B/S
// rule applied as per-count bit-equality indicators.  Measured ~24x the byte
// engine's throughput per core; the byte path remains the general
// fallback (any radius, any width).
//
// Layout: (rows + 2) x nw words, one ghost row above and below (periodic
// rows copied, dead rows zeroed, each generation); LSB of word j = column
// j*64; horizontal neighbors come from 1-bit shifts with cross-word carry
// bits, ghost columns from the wrapped (periodic) or zero (dead) carry.
// ---------------------------------------------------------------------------

struct SwarScratch {
    std::vector<uint64_t> f0, f1, c0, c1;
    explicit SwarScratch(int64_t nw) : f0(nw), f1(nw), c0(nw), c1(nw) {}
};

// One generation over rows [lo, hi) (1-based interior rows of the padded
// buffer).  Reads cur (with valid ghost rows), writes nxt interior.
void swar_gen_rows(const uint64_t* cur, uint64_t* nxt, int64_t nw,
                   int64_t lo, int64_t hi, bool periodic,
                   const uint8_t* birth, const uint8_t* survive,
                   SwarScratch& s) {
    for (int64_t i = lo; i < hi; ++i) {
        const uint64_t* u = cur + (i - 1) * nw;
        const uint64_t* m = cur + i * nw;
        const uint64_t* d = cur + (i + 1) * nw;
        for (int64_t j = 0; j < nw; ++j) {
            const uint64_t a = u[j], b = m[j], c = d[j];
            const uint64_t t = a ^ b;
            s.f0[j] = t ^ c;                 // vertical sum, weight 1
            s.f1[j] = (a & b) | (c & t);     // vertical sum, weight 2 (majority)
            s.c0[j] = a ^ c;                 // center-excluded vertical sum
            s.c1[j] = a & c;
        }
        uint64_t* out = nxt + i * nw;
        for (int64_t j = 0; j < nw; ++j) {
            // column sums of the left/right neighbor columns: this word's
            // sums shifted by one bit, carry bit from the adjacent word
            // (wrapped under periodic columns, zero under dead)
            const int64_t jp = j > 0 ? j - 1 : nw - 1;
            const int64_t jn = j < nw - 1 ? j + 1 : 0;
            const bool wl = j > 0 || periodic;   // left carry word exists
            const bool wr = j < nw - 1 || periodic;
            const uint64_t p0 = wl ? s.f0[jp] : 0, p1 = wl ? s.f1[jp] : 0;
            const uint64_t q0 = wr ? s.f0[jn] : 0, q1 = wr ? s.f1[jn] : 0;
            const uint64_t l0 = (s.f0[j] << 1) | (p0 >> 63);
            const uint64_t l1 = (s.f1[j] << 1) | (p1 >> 63);
            const uint64_t r0 = (s.f0[j] >> 1) | (q0 << 63);
            const uint64_t r1 = (s.f1[j] >> 1) | (q1 << 63);
            // count = left + right + center-excluded middle: two bit-sliced
            // 2-bit adds producing count bits n0..n3 (0..8)
            const uint64_t s0 = l0 ^ r0, car0 = l0 & r0;
            const uint64_t x1 = l1 ^ r1;
            const uint64_t s1 = x1 ^ car0;
            const uint64_t car1 = (l1 & r1) | (car0 & x1);
            const uint64_t n0 = s0 ^ s.c0[j], k0 = s0 & s.c0[j];
            const uint64_t y1 = s1 ^ s.c1[j];
            const uint64_t n1 = y1 ^ k0;
            const uint64_t k1 = (s1 & s.c1[j]) | (k0 & y1);
            const uint64_t n2 = car1 ^ k1;
            const uint64_t n3 = car1 & k1;
            uint64_t bi = 0, si = 0;
            for (int k = 0; k <= 8; ++k) {
                if (!birth[k] && !survive[k]) continue;
                const uint64_t eq = ((k & 1) ? n0 : ~n0) & ((k & 2) ? n1 : ~n1) &
                                    ((k & 4) ? n2 : ~n2) & ((k & 8) ? n3 : ~n3);
                if (birth[k]) bi |= eq;
                if (survive[k]) si |= eq;
            }
            const uint64_t alive = m[j];
            out[j] = (alive & si) | (~alive & bi);
        }
    }
}

static void ltl_fill_ghost_rows(uint64_t* buf, int64_t rows, int64_t nw,
                                int r, bool periodic);

void swar_fill_ghost_rows(uint64_t* buf, int64_t rows, int64_t nw, bool periodic) {
    ltl_fill_ghost_rows(buf, rows, nw, 1, periodic);
}

// ghost = leading ghost rows in buf (1 for the padded layout, 0 interior-only)
void swar_pack(const uint8_t* grid, uint64_t* buf, int64_t rows, int64_t cols,
               int ghost) {
    const int64_t nw = cols / 64;
    for (int64_t i = 0; i < rows; ++i) {
        const uint8_t* row = grid + i * cols;
        uint64_t* prow = buf + (i + ghost) * nw;
        for (int64_t j = 0; j < nw; ++j) {
            uint64_t w = 0;
            for (int b = 0; b < 64; ++b)
                w |= (uint64_t)(row[j * 64 + b] & 1) << b;
            prow[j] = w;
        }
    }
}

void swar_unpack(const uint64_t* buf, uint8_t* grid, int64_t rows, int64_t cols,
                 int ghost) {
    const int64_t nw = cols / 64;
    for (int64_t i = 0; i < rows; ++i) {
        uint8_t* row = grid + i * cols;
        const uint64_t* prow = buf + (i + ghost) * nw;
        for (int64_t j = 0; j < nw; ++j)
            for (int b = 0; b < 64; ++b)
                row[j * 64 + b] = (prow[j] >> b) & 1u;
    }
}

bool swar_eligible(int64_t cols, int radius) {
    return radius == 1 && cols % 64 == 0 && cols > 0;
}

// ---------------------------------------------------------------------------
// Bit-sliced radius-r (Larger-than-Life) engine — the native mirror of
// ops/bitltl.py.  Per-cell integers live as uint64 bit planes (plane k
// holds bit k of each cell's value, 64 cells per word): a ripple
// carry-save accumulation of the 2r+1 vertically adjacent row words
// builds each column's sum (<=4 planes), shifted copies with cross-word
// carry bits are ripple-added into the <=8-plane neighborhood total, and
// B/S membership is an MSB-first bit-sliced comparator over count
// intervals derived from the rule tables.  The total includes the center
// cell, so survive intervals are tested shifted by +1 (no bit-sliced
// subtraction), exactly as the Python engine does.
// ---------------------------------------------------------------------------

static std::vector<std::pair<int, int>> table_intervals(const uint8_t* t,
                                                        int n) {
    std::vector<std::pair<int, int>> out;
    int lo = -1;
    for (int c = 0; c <= n; ++c) {
        const bool on = c < n && t[c];
        if (on && lo < 0) lo = c;
        if (!on && lo >= 0) { out.push_back({lo, c - 1}); lo = -1; }
    }
    return out;
}

static inline int bit_len(int v) {
    int n = 0;
    while (v >> n) ++n;
    return n;
}

// mask of cells whose bit-sliced value (planes t[0..np), LSB first) >= T
static inline uint64_t bs_ge_word(const uint64_t* t, int np, int T) {
    if (T <= 0) return ~0ull;
    if (T >= (1 << np)) return 0ull;
    uint64_t gt = 0, eq = ~0ull;
    for (int k = np - 1; k >= 0; --k) {
        const uint64_t p = t[k];
        if ((T >> k) & 1) {
            eq &= p;
        } else {
            gt |= eq & p;
            eq &= ~p;
        }
    }
    return gt | eq;
}

// ripple-add b (nb planes) into a (na planes); na must cover the maximum
static inline void add_planes(uint64_t* a, int na, const uint64_t* b, int nb) {
    uint64_t carry = 0;
    for (int p = 0; p < na; ++p) {
        const uint64_t x = a[p], y = p < nb ? b[p] : 0;
        const uint64_t t = x ^ y;
        a[p] = t ^ carry;
        carry = (x & y) | (carry & t);
    }
}

// (A carry-save 3:2-compressor accumulator — the Wallace-tree shape the
// Python engine's bs_sum uses, ops/bitltl.py — was tried here and
// MEASURED SLOWER on CPU: 0.35 vs 0.42 Gcell/s for Bosco at 2048², one
// core.  The per-weight bucket arrays force stack traffic and dynamic
// indexing where the ripple chains keep t[]/addL/addR in registers with
// plenty of scalar ILP; the op-count saving only pays on wide-vector
// machines, which is why the TPU engines use bs_sum and this one keeps
// sequential add_planes.)

// one generation of rows [lo_row, hi_row) on an r-ghost-row padded packed
// buffer; vplanes is nv*nw scratch for the per-row vertical sums
static void ltl_gen_rows(const uint64_t* cur, uint64_t* nxt, int64_t nw,
                         int64_t lo_row, int64_t hi_row, int r, bool periodic,
                         const std::vector<std::pair<int, int>>& birth_iv,
                         const std::vector<std::pair<int, int>>& survive_iv,
                         int nv, int np, uint64_t* vplanes) {
    for (int64_t i = lo_row; i < hi_row; ++i) {
        for (int64_t j = 0; j < nw; ++j) {
            uint64_t planes[4] = {0, 0, 0, 0};
            for (int d = -r; d <= r; ++d) {
                uint64_t bit = cur[(i + d) * nw + j];
                for (int p = 0; p < nv; ++p) {
                    const uint64_t s = planes[p] ^ bit;
                    bit = planes[p] & bit;
                    planes[p] = s;
                }
            }
            for (int p = 0; p < nv; ++p) vplanes[p * nw + j] = planes[p];
        }
        uint64_t* out = nxt + i * nw;
        for (int64_t j = 0; j < nw; ++j) {
            const int64_t jp = j > 0 ? j - 1 : nw - 1;
            const int64_t jn = j < nw - 1 ? j + 1 : 0;
            const bool wl = j > 0 || periodic;
            const bool wr = j < nw - 1 || periodic;
            uint64_t t[8] = {0, 0, 0, 0, 0, 0, 0, 0};
            for (int p = 0; p < nv; ++p) t[p] = vplanes[p * nw + j];
            for (int d = 1; d <= r; ++d) {
                uint64_t addL[4], addR[4];
                for (int p = 0; p < nv; ++p) {
                    const uint64_t vj = vplanes[p * nw + j];
                    const uint64_t vp = wl ? vplanes[p * nw + jp] : 0;
                    const uint64_t vn = wr ? vplanes[p * nw + jn] : 0;
                    addL[p] = (vj << d) | (vp >> (64 - d));  // column j-d
                    addR[p] = (vj >> d) | (vn << (64 - d));  // column j+d
                }
                add_planes(t, np, addL, nv);
                add_planes(t, np, addR, nv);
            }
            uint64_t born = 0, stay = 0;
            for (const auto& iv : birth_iv)
                born |= bs_ge_word(t, np, iv.first) &
                        ~bs_ge_word(t, np, iv.second + 1);
            // total = count + 1 for alive cells (center included)
            for (const auto& iv : survive_iv)
                stay |= bs_ge_word(t, np, iv.first + 1) &
                        ~bs_ge_word(t, np, iv.second + 2);
            const uint64_t alive = cur[i * nw + j];
            out[j] = (alive & stay) | (~alive & born);
        }
    }
}

static void ltl_fill_ghost_rows(uint64_t* buf, int64_t rows, int64_t nw,
                                int r, bool periodic) {
    for (int g = 0; g < r; ++g) {
        uint64_t* top = buf + g * nw;
        uint64_t* bot = buf + (rows + r + g) * nw;
        if (periodic) {
            // top ghost g is global row rows-r+g = buffer row rows+g;
            // bottom ghost g is global row g = buffer row r+g
            std::memcpy(top, buf + (rows + g) * nw, (size_t)nw * 8);
            std::memcpy(bot, buf + (r + g) * nw, (size_t)nw * 8);
        } else {
            std::memset(top, 0, (size_t)nw * 8);
            std::memset(bot, 0, (size_t)nw * 8);
        }
    }
}

bool ltl_eligible(int64_t rows, int64_t cols, int radius) {
    return radius > 1 && radius <= 7 && cols % 64 == 0 && cols > 0 &&
           rows >= 2 * radius + 1;
}

void ltl_evolve(uint8_t* grid, int64_t rows, int64_t cols, int64_t steps,
                const uint8_t* birth_table, const uint8_t* survive_table,
                int r, bool periodic) {
    const int64_t nw = cols / 64;
    const int side = 2 * r + 1;
    const int nmax = side * side - 1;
    const int nv = bit_len(side);       // vertical sums reach 2r+1
    const int np = bit_len(side * side);  // totals reach (2r+1)^2
    const auto birth_iv = table_intervals(birth_table, nmax + 1);
    const auto survive_iv = table_intervals(survive_table, nmax + 1);
    std::vector<uint64_t> a((size_t)((rows + 2 * r) * nw), 0);
    std::vector<uint64_t> b((size_t)((rows + 2 * r) * nw), 0);
    std::vector<uint64_t> vplanes((size_t)(nv * nw));
    swar_pack(grid, a.data(), rows, cols, r);
    uint64_t *cur = a.data(), *nxt = b.data();
    for (int64_t s = 0; s < steps; ++s) {
        ltl_fill_ghost_rows(cur, rows, nw, r, periodic);
        ltl_gen_rows(cur, nxt, nw, r, rows + r, r, periodic,
                     birth_iv, survive_iv, nv, np, vplanes.data());
        std::swap(cur, nxt);
    }
    swar_unpack(cur, grid, rows, cols, r);
}

// ---------------------------------------------------------------------------
// Temporal blocking for DRAM-resident grids — the CPU mirror of the Pallas
// kernel's gens-deep VMEM blocking (ops/pallas_bitlife.py): each sweep
// advances independent row blocks G generations inside a cache-resident
// slab (block rows + 2G halo rows + 1 ghost row per side), touching DRAM
// once per G generations instead of once per generation.  Neighboring
// blocks recompute each other's halo rows redundantly from the same
// source sweep (overlapped/trapezoidal tiling), so blocks — and threads —
// stay independent between barriers.
// ---------------------------------------------------------------------------

struct SwarSlab {
    std::vector<uint64_t> a, b;
    SwarScratch scratch;
    SwarSlab(int64_t max_slab_rows, int64_t nw)
        : a((size_t)(max_slab_rows * nw)),
          b((size_t)(max_slab_rows * nw)),
          scratch(nw) {}
};

// Packed-grid bytes above which the temporally-blocked sweeps kick in.
// Default: disabled — measured on this machine (1 core, 16384², 16
// steps) the plain per-generation sweep is compute-bound at ~0.7 GB/s of
// traffic, and blocking's slab copies + redundant halo rows cost more
// than the cache locality earns (2.85 → 2.40 Gcell/s).  The machinery
// stays available (GOLCORE_SWAR_BLOCK_THRESHOLD=bytes) for hosts where
// many cores share DRAM bandwidth and the plain sweep *is* memory-bound;
// tests force 0 to pin its correctness.
int64_t swar_block_threshold() {
    const char* e = std::getenv("GOLCORE_SWAR_BLOCK_THRESHOLD");
    return e ? std::atoll(e) : INT64_MAX;
}

// Pick the block height so one slab buffer stays cache-resident.
int64_t swar_pick_block_rows(int64_t nw, int64_t G) {
    const int64_t budget = 768 << 10;  // bytes per slab buffer (~L2-sized)
    int64_t S = budget / (nw * 8);
    int64_t B = S - 2 * G - 2;
    if (B < 32) return 0;  // rows too wide to block profitably
    if (B > 512) B = 512;
    return B;
}

// One G-generation sweep over blocks [blk0, blk1) of height B: reads the
// full src grid (interior-only, rows x nw), writes those blocks' rows of
// dst stepped G generations.
void swar_blocked_sweep(const uint64_t* src, uint64_t* dst, int64_t rows,
                        int64_t nw, bool periodic, const uint8_t* birth,
                        const uint8_t* survive, int64_t G, int64_t B,
                        int64_t blk0, int64_t blk1, SwarSlab& slab) {
    for (int64_t blk = blk0; blk < blk1; ++blk) {
        const int64_t base = blk * B;
        const int64_t Beff = std::min(B, rows - base);
        const int64_t S = Beff + 2 * G + 2;  // slab rows incl. ghosts
        uint64_t* cur = slab.a.data();
        uint64_t* nxt = slab.b.data();
        // slab row s holds grid row base - G - 1 + s (wrapped / zeroed)
        for (int64_t s = 0; s < S; ++s) {
            int64_t r = base - G - 1 + s;
            if (periodic) {
                r = ((r % rows) + rows) % rows;
                std::memcpy(cur + s * nw, src + r * nw, (size_t)nw * 8);
            } else if (r < 0 || r >= rows) {
                std::memset(cur + s * nw, 0, (size_t)nw * 8);
            } else {
                std::memcpy(cur + s * nw, src + r * nw, (size_t)nw * 8);
            }
        }
        for (int64_t g = 0; g < G; ++g) {
            // validity shrinks one row per side per generation
            swar_gen_rows(cur, nxt, nw, 1 + g, S - 1 - g, periodic, birth,
                          survive, slab.scratch);
            if (!periodic) {
                // slab rows outside the grid are not real cells; live grid
                // neighbors "give birth" into them — re-kill after every
                // in-slab generation (same discipline as the Pallas
                // kernel's edge blocks and the overlap steppers)
                const int64_t lead = std::max<int64_t>(0, G + 1 - base);
                const int64_t tail =
                    std::max<int64_t>(0, (base + Beff + G + 1) - rows);
                for (int64_t s = 1 + g; s < std::min(lead, S - 1 - g); ++s)
                    std::memset(nxt + s * nw, 0, (size_t)nw * 8);
                for (int64_t s = std::max(S - tail, 1 + g); s < S - 1 - g; ++s)
                    std::memset(nxt + s * nw, 0, (size_t)nw * 8);
            }
            std::swap(cur, nxt);
        }
        std::memcpy(dst + base * nw, cur + (1 + G) * nw,
                    (size_t)(Beff * nw) * 8);
    }
}

// Evolve an interior-only packed grid `steps` generations with temporal
// blocking, `threads_n` workers owning disjoint block ranges per sweep.
// One code path for any worker count (a 1-thread group pays one spawn per
// evolve call, not per step); the final-result buffer is bufs[sweeps % 2].
void swar_evolve_blocked(uint64_t* grid0, uint64_t* grid1, int64_t rows,
                         int64_t nw, bool periodic, const uint8_t* birth,
                         const uint8_t* survive, int64_t steps, int64_t B,
                         int64_t G, int threads_n) {
    const int64_t nblocks = (rows + B - 1) / B;
    if (threads_n > nblocks) threads_n = (int)nblocks;
    if (threads_n < 1) threads_n = 1;
    uint64_t* bufs[2] = {grid0, grid1};
    Barrier barrier(threads_n);
    std::vector<std::thread> threads;
    threads.reserve((size_t)threads_n);
    for (int t = 0; t < threads_n; ++t) {
        const int64_t b0 = nblocks * t / threads_n;
        const int64_t b1 = nblocks * (t + 1) / threads_n;
        threads.emplace_back([=, &barrier]() {
            SwarSlab slab(B + 2 * G + 2, nw);
            int cur = 0;
            int64_t done = 0;
            while (done < steps) {
                const int64_t g = std::min(G, steps - done);
                swar_blocked_sweep(bufs[cur], bufs[1 - cur], rows, nw,
                                   periodic, birth, survive, g, B, b0, b1,
                                   slab);
                cur = 1 - cur;
                done += g;
                barrier.arrive_and_wait();  // all blocks of this sweep done
            }
        });
    }
    for (auto& th : threads) th.join();
    const int64_t sweeps = (steps + G - 1) / G;
    if (sweeps % 2)
        std::memcpy(grid0, grid1, (size_t)(rows * nw) * 8);
}

// Shared dispatch for both public entry points: run the blocked engine if
// the grid qualifies (returns true), else leave it to the caller's plain
// path.  Keeping the G/B/threshold policy in ONE place so the two entry
// points cannot drift.
bool swar_try_blocked(uint8_t* grid, int64_t rows, int64_t cols,
                      const uint8_t* birth, const uint8_t* survive,
                      int64_t steps, int periodic, int threads_n) {
    const int64_t nw = cols / 64;
    const int64_t G = std::min<int64_t>(8, steps);
    const int64_t B = swar_pick_block_rows(nw, G);
    if (steps < 2 || B <= 0 || rows * nw * 8 <= swar_block_threshold())
        return false;
    std::vector<uint64_t> a((size_t)(rows * nw), 0);
    std::vector<uint64_t> b((size_t)(rows * nw), 0);
    swar_pack(grid, a.data(), rows, cols, 0);
    swar_evolve_blocked(a.data(), b.data(), rows, nw, periodic != 0, birth,
                        survive, steps, B, G, threads_n);
    swar_unpack(a.data(), grid, rows, cols, 0);
    return true;
}

// Fill the ghost ring of a standalone padded buffer from its own interior
// (periodic) or zeros (dead).  Used by the serial engine.
void fill_ghosts_self(uint8_t* buf, int64_t rows, int64_t cols, int r, bool periodic) {
    const int64_t pw = cols + 2 * r;
    const int64_t ph = rows + 2 * r;
    if (!periodic) {
        for (int64_t i = 0; i < ph; ++i) {
            uint8_t* row = buf + i * pw;
            if (i < r || i >= rows + r) {
                std::memset(row, 0, pw);
            } else {
                std::memset(row, 0, r);
                std::memset(row + cols + r, 0, r);
            }
        }
        return;
    }
    // periodic: wrap rows then columns (row pass first so column wrap copies
    // the already-wrapped rows — corners come out right).
    for (int k = 0; k < r; ++k) {
        std::memcpy(buf + k * pw + r, buf + (rows + k) * pw + r, cols);
        std::memcpy(buf + (rows + r + k) * pw + r, buf + (r + k) * pw + r, cols);
    }
    for (int64_t i = 0; i < ph; ++i) {
        uint8_t* row = buf + i * pw;
        for (int k = 0; k < r; ++k) {
            row[k] = row[cols + k];
            row[cols + r + k] = row[r + k];
        }
    }
}


// ---------------------------------------------------------------------------
// Parallel engine: tile mesh + ghost-ring halo exchange.
// ---------------------------------------------------------------------------

struct Tile {
    int64_t r0, c0, rows, cols;  // interior placement in the global grid
    std::vector<uint8_t> a, b;   // double-buffered padded storage
    std::vector<uint8_t> rowsum;
};

struct ParEngine {
    int ti, tj, radius;
    bool periodic;
    std::vector<Tile> tiles;

    Tile& at(int i, int j) { return tiles[(size_t)i * tj + j]; }

    // Neighbor tile index along one axis, honoring boundary; -1 = none (dead).
    int wrap(int x, int n) const {
        if (x >= 0 && x < n) return x;
        return periodic ? (x + n) % n : -1;
    }
};

// Copy a rect from src tile's CURRENT interior into dst tile's padded buffer.
// Coordinates are interior-relative (0-based); dst offsets are padded-buffer
// absolute.  cur selects which double buffer is "current" this step.
inline void copy_rect(const Tile& src, const std::vector<uint8_t>& src_buf, int r,
                      int64_t si, int64_t sj, Tile& dst, std::vector<uint8_t>& dst_buf,
                      int64_t di, int64_t dj, int64_t h, int64_t w) {
    const int64_t spw = src.cols + 2 * r;
    const int64_t dpw = dst.cols + 2 * r;
    for (int64_t k = 0; k < h; ++k) {
        std::memcpy(dst_buf.data() + (di + k) * dpw + dj,
                    src_buf.data() + (si + r + k) * spw + sj + r, w);
    }
}

// Fill every ghost slab of tile (i, j) from its 8 mesh neighbors' interiors —
// the shared-memory distr_borders.  Reads neighbors' current buffers (stable
// during the exchange phase; a barrier separates exchange from compute).
void exchange_tile(ParEngine& e, int i, int j, bool cur_is_a) {
    Tile& t = e.at(i, j);
    std::vector<uint8_t>& dst = cur_is_a ? t.a : t.b;
    const int r = e.radius;
    const int64_t pw = t.cols + 2 * r;

    for (int di = -1; di <= 1; ++di) {
        for (int dj = -1; dj <= 1; ++dj) {
            if (di == 0 && dj == 0) continue;
            // Destination slab in t's padded buffer.
            int64_t dst_i = di < 0 ? 0 : (di == 0 ? r : t.rows + r);
            int64_t dst_j = dj < 0 ? 0 : (dj == 0 ? r : t.cols + r);
            int64_t h = di == 0 ? t.rows : r;
            int64_t w = dj == 0 ? t.cols : r;
            int ni = e.wrap(i + di, e.ti);
            int nj = e.wrap(j + dj, e.tj);
            if (ni < 0 || nj < 0) {
                for (int64_t k = 0; k < h; ++k)
                    std::memset(dst.data() + (dst_i + k) * pw + dst_j, 0, w);
                continue;
            }
            Tile& s = e.at(ni, nj);
            const std::vector<uint8_t>& src = cur_is_a ? s.a : s.b;
            // Source rect: the neighbor's interior edge facing us.
            int64_t si = di < 0 ? s.rows - r : 0;  // coming from above: its bottom
            int64_t sj = dj < 0 ? s.cols - r : 0;
            copy_rect(s, src, r, si, sj, t, dst, dst_i, dst_j, h, w);
        }
    }
}

}  // namespace

extern "C" {

// Fill a (rows x cols) uint8 tile of the global grid starting at
// (row_off, col_off); alive iff hash % 3 == 0 (P = 1/3, matching the
// reference's rand() % 3 == 0 density, main.cpp:69-73).
void gol_init(uint8_t* grid, int64_t rows, int64_t cols, uint32_t seed,
              int64_t row_off, int64_t col_off) {
    for (int64_t i = 0; i < rows; ++i) {
        uint32_t gi = (uint32_t)(row_off + i);
        for (int64_t j = 0; j < cols; ++j) {
            uint32_t gj = (uint32_t)(col_off + j);
            grid[i * cols + j] = cell_hash(seed, gi, gj) % 3u == 0u;
        }
    }
}

// One serial step: in/out are UNPADDED (rows x cols) buffers.
void gol_step(const uint8_t* in, uint8_t* out, int64_t rows, int64_t cols,
              const uint8_t* birth_table, const uint8_t* survive_table,
              int radius, int periodic) {
    const int r = radius;
    const int64_t pw = cols + 2 * r, ph = rows + 2 * r;
    std::vector<uint8_t> pin((size_t)(ph * pw)), pout((size_t)(ph * pw));
    std::vector<uint8_t> rowsum((size_t)(rows * pw));
    for (int64_t i = 0; i < rows; ++i)
        std::memcpy(pin.data() + (i + r) * pw + r, in + i * cols, cols);
    fill_ghosts_self(pin.data(), rows, cols, r, periodic != 0);
    RuleTables rule{birth_table, survive_table, r};
    step_padded(pin.data(), pout.data(), rows, cols, rule, rowsum.data());
    for (int64_t i = 0; i < rows; ++i)
        std::memcpy(out + i * cols, pout.data() + (i + r) * pw + r, cols);
}

// Serial evolution, double buffered in padded space; result lands in grid.
// Radius-1 rules on 64-aligned widths take the bitpacked SWAR fast path.
void gol_evolve(uint8_t* grid, int64_t rows, int64_t cols, int64_t steps,
                const uint8_t* birth_table, const uint8_t* survive_table,
                int radius, int periodic) {
    if (ltl_eligible(rows, cols, radius) && steps > 0) {
        ltl_evolve(grid, rows, cols, steps, birth_table, survive_table,
                   radius, periodic != 0);
        return;
    }
    if (swar_eligible(cols, radius) && rows >= 1 && steps > 0) {
        const int64_t nw = cols / 64;
        if (swar_try_blocked(grid, rows, cols, birth_table, survive_table,
                             steps, periodic, 1))
            return;
        std::vector<uint64_t> a((size_t)((rows + 2) * nw), 0);
        std::vector<uint64_t> b((size_t)((rows + 2) * nw), 0);
        swar_pack(grid, a.data(), rows, cols, 1);
        SwarScratch scr(nw);
        uint64_t *cur = a.data(), *nxt = b.data();
        for (int64_t s = 0; s < steps; ++s) {
            swar_fill_ghost_rows(cur, rows, nw, periodic != 0);
            swar_gen_rows(cur, nxt, nw, 1, rows + 1, periodic != 0,
                          birth_table, survive_table, scr);
            std::swap(cur, nxt);
        }
        swar_unpack(cur, grid, rows, cols, 1);
        return;
    }
    const int r = radius;
    const int64_t pw = cols + 2 * r, ph = rows + 2 * r;
    std::vector<uint8_t> a((size_t)(ph * pw)), b((size_t)(ph * pw));
    std::vector<uint8_t> rowsum((size_t)(rows * pw));
    for (int64_t i = 0; i < rows; ++i)
        std::memcpy(a.data() + (i + r) * pw + r, grid + i * cols, cols);
    RuleTables rule{birth_table, survive_table, r};
    uint8_t *cur = a.data(), *nxt = b.data();
    for (int64_t s = 0; s < steps; ++s) {
        fill_ghosts_self(cur, rows, cols, r, periodic != 0);
        step_padded(cur, nxt, rows, cols, rule, rowsum.data());
        std::swap(cur, nxt);
    }
    for (int64_t i = 0; i < rows; ++i)
        std::memcpy(grid + i * cols, cur + (i + r) * pw + r, cols);
}

// Parallel evolution over a ti x tj worker-tile mesh (one thread per tile).
// Requires rows % ti == 0 and cols % tj == 0; returns 0 on success.
// worker_us (nullable): ti*tj slots, each ACCUMULATING its worker thread's
// measured wall time inside the evolve loop (includes barrier waits — the
// per-rank duration the reference's MPI_Reduce summed, main.cpp:319-324);
// accumulation lets segmented callers (snapshot gaps) total across calls.
int gol_evolve_par_t(uint8_t* grid, int64_t rows, int64_t cols, int64_t steps,
                     const uint8_t* birth_table, const uint8_t* survive_table,
                     int radius, int periodic, int ti, int tj,
                     int64_t* worker_us) {
    if (ti < 1 || tj < 1 || rows % ti || cols % tj) return 1;
    if (swar_eligible(cols, radius) && rows >= 1) {
        // Packed engine: the requested ti x tj mesh supplies the worker
        // count; internally workers own contiguous row BANDS of the one
        // packed global buffer (no per-tile ghosts to exchange — a band's
        // neighbor rows are just the adjacent bands' rows, stable during
        // the compute phase between barriers).  Results are identical to
        // the tile engine: same CA, same global grid.
        int w = ti * tj;
        if ((int64_t)w > rows) w = (int)rows;
        const int64_t nw = cols / 64;
        {
            auto b0 = std::chrono::steady_clock::now();
            if (swar_try_blocked(grid, rows, cols, birth_table, survive_table,
                                 steps, periodic, w)) {
                if (worker_us) {
                    // the blocked engine forks/joins its workers every block
                    // row, so each worker's measured span is the whole call.
                    // Credit >= 1us so a nonzero slot reliably means "this
                    // worker ran" (gol_main derives the active-worker count
                    // from nonzero slots) even when the span truncates to 0.
                    int64_t us = std::chrono::duration_cast<
                        std::chrono::microseconds>(
                        std::chrono::steady_clock::now() - b0).count();
                    if (us < 1) us = 1;
                    for (int t = 0; t < w; ++t) worker_us[t] += us;
                }
                return 0;
            }
        }
        std::vector<uint64_t> a((size_t)((rows + 2) * nw), 0);
        std::vector<uint64_t> b((size_t)((rows + 2) * nw), 0);
        swar_pack(grid, a.data(), rows, cols, 1);
        if (steps > 0) {
            Barrier barrier(w);
            std::vector<std::thread> threads;
            threads.reserve((size_t)w);
            uint64_t* bufs[2] = {a.data(), b.data()};
            for (int t = 0; t < w; ++t) {
                const int64_t lo = 1 + rows * t / w;
                const int64_t hi = 1 + rows * (t + 1) / w;
                threads.emplace_back([=, &barrier]() {
                    auto w0 = std::chrono::steady_clock::now();
                    SwarScratch scr(nw);
                    int cur = 0;
                    for (int64_t s = 0; s < steps; ++s) {
                        if (lo == 1)  // first band owns the ghost rows
                            swar_fill_ghost_rows(bufs[cur], rows, nw,
                                                 periodic != 0);
                        barrier.arrive_and_wait();  // ghosts valid
                        swar_gen_rows(bufs[cur], bufs[1 - cur], nw, lo, hi,
                                      periodic != 0, birth_table,
                                      survive_table, scr);
                        cur = 1 - cur;
                        barrier.arrive_and_wait();  // all bands written
                    }
                    if (worker_us) {
                        int64_t us = std::chrono::duration_cast<
                            std::chrono::microseconds>(
                            std::chrono::steady_clock::now() - w0).count();
                        worker_us[t] += us < 1 ? 1 : us;  // nonzero == ran
                    }
                });
            }
            for (auto& th : threads) th.join();
        }
        swar_unpack(steps % 2 ? b.data() : a.data(), grid, rows, cols, 1);
        return 0;
    }
    const int r = radius;
    const int64_t trows = rows / ti, tcols = cols / tj;
    if (trows < r || tcols < r) return 2;  // ghost slab must fit in one neighbor

    ParEngine e;
    e.ti = ti; e.tj = tj; e.radius = r; e.periodic = periodic != 0;
    e.tiles.resize((size_t)ti * tj);
    const int64_t pw = tcols + 2 * r, ph = trows + 2 * r;
    for (int i = 0; i < ti; ++i) {
        for (int j = 0; j < tj; ++j) {
            Tile& t = e.at(i, j);
            t.r0 = i * trows; t.c0 = j * tcols; t.rows = trows; t.cols = tcols;
            t.a.assign((size_t)(ph * pw), 0);
            t.b.assign((size_t)(ph * pw), 0);
            t.rowsum.assign((size_t)(trows * pw), 0);
            for (int64_t k = 0; k < trows; ++k)
                std::memcpy(t.a.data() + (k + r) * pw + r,
                            grid + (t.r0 + k) * cols + t.c0, tcols);
        }
    }

    Barrier barrier(ti * tj);
    std::vector<std::thread> workers;
    workers.reserve((size_t)ti * tj);
    for (int i = 0; i < ti; ++i) {
        for (int j = 0; j < tj; ++j) {
            workers.emplace_back([&e, &barrier, i, j, steps, birth_table,
                                  survive_table, worker_us]() {
                auto w0 = std::chrono::steady_clock::now();
                Tile& t = e.at(i, j);
                RuleTables rule{birth_table, survive_table, e.radius};
                bool cur_is_a = true;
                for (int64_t s = 0; s < steps; ++s) {
                    exchange_tile(e, i, j, cur_is_a);
                    barrier.arrive_and_wait();  // all ghosts filled
                    uint8_t* cur = cur_is_a ? t.a.data() : t.b.data();
                    uint8_t* nxt = cur_is_a ? t.b.data() : t.a.data();
                    step_padded(cur, nxt, t.rows, t.cols, rule, t.rowsum.data());
                    cur_is_a = !cur_is_a;
                    barrier.arrive_and_wait();  // all interiors written
                }
                if (worker_us) {
                    int64_t us = std::chrono::duration_cast<
                        std::chrono::microseconds>(
                        std::chrono::steady_clock::now() - w0).count();
                    worker_us[(size_t)i * e.tj + j] += us < 1 ? 1 : us;
                }
            });
        }
    }
    for (auto& w : workers) w.join();

    const bool final_is_a = (steps % 2) == 0;
    for (int i = 0; i < ti; ++i) {
        for (int j = 0; j < tj; ++j) {
            Tile& t = e.at(i, j);
            const uint8_t* buf = final_is_a ? t.a.data() : t.b.data();
            for (int64_t k = 0; k < trows; ++k)
                std::memcpy(grid + (t.r0 + k) * cols + t.c0,
                            buf + (k + r) * pw + r, tcols);
        }
    }
    return 0;
}

// Untimed entry (the ctypes binding's stable surface).
int gol_evolve_par(uint8_t* grid, int64_t rows, int64_t cols, int64_t steps,
                   const uint8_t* birth_table, const uint8_t* survive_table,
                   int radius, int periodic, int ti, int tj) {
    return gol_evolve_par_t(grid, rows, cols, steps, birth_table,
                            survive_table, radius, periodic, ti, tj, nullptr);
}

}  // extern "C"
