// gol_native — standalone native CLI, runnable without Python.
//
// The reference ships two standalone binaries (./gol via mpirun and
// ./gol_serial); this is the framework's equivalent front end over the
// golcore engine: same positional contract
//     rows cols iteration_gap iterations [time_file] [first]
// (reference main.cpp:171-223) plus flags for what the reference
// hardcoded: --workers N (multi-worker tile engine; the mpirun -np
// analog), --boundary periodic|dead, --rule NAME (built-ins plus the
// same 'B3/S23' / 'R5,B34-45,S33-57' grammar as models/rules.py, any
// radius 1..7), --seed S, --save, --out-dir D, --name N.
//
// Emits the same .gol master/tile format as the Python CLI (golio.py) —
// one tile per worker with global coordinates, like each MPI rank's own
// dump in the reference (main.cpp:106-129) — so
// tools/gol_visualization.py and the parity tests consume its output
// directly, and appends the reference-schema 12-column timing CSV
// (main.cpp:356-363) with correctly-labeled microseconds.

#include <cctype>
#include <chrono>
#include <climits>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <fstream>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void gol_init(uint8_t*, int64_t, int64_t, uint32_t, int64_t, int64_t);
void gol_evolve(uint8_t*, int64_t, int64_t, int64_t, const uint8_t*,
                const uint8_t*, int, int);
int gol_evolve_par(uint8_t*, int64_t, int64_t, int64_t, const uint8_t*,
                   const uint8_t*, int, int, int, int);
}

namespace {

// An outer-totalistic rule as the engine consumes it: count-indexed birth/
// survive tables of size (2r+1)^2 (the form models/rules.py `tables()`
// produces for the ctypes path — one grammar, two front ends).
struct ParsedRule {
    int radius = 1;
    std::vector<uint8_t> birth, survive;
};

// Built-ins route through the same string grammar as the Python registry
// (models/rules.py LIFE/HIGHLIFE/SEEDS/DAY_AND_NIGHT/BOSCO).
const char* builtin_rule(const std::string& n) {
    if (n == "life") return "b3/s23";
    if (n == "highlife") return "b36/s23";
    if (n == "seeds") return "b2/s";
    if (n == "daynight") return "b3678/s34678";
    if (n == "bosco") return "r5,b34-45,s33-57";
    return nullptr;
}

// "b<digits>/s<digits>" (radius 1) or "r<N>,b<ranges>,s<ranges>" where
// ranges are '+'-joined "lo-hi" / single counts — mirrors
// rules.rule_from_name exactly.  Returns false on parse/validation error.
bool parse_rule(std::string s, ParsedRule& out, std::string& err) {
    for (auto& c : s) c = (char)tolower(c);
    if (const char* b = builtin_rule(s)) s = b;

    // Non-digit characters are skipped (Python: `if ch.isdigit()`), but an
    // out-of-range digit errors (Python: Rule.__post_init__ range check) —
    // B9/S23 must fail the same way in both front ends.
    auto add_counts_digits = [](const std::string& part, std::vector<uint8_t>& t) -> bool {
        for (char c : part) {
            if (c < '0' || c > '9') continue;
            if ((size_t)(c - '0') >= t.size()) return false;
            t[(size_t)(c - '0')] = 1;
        }
        return true;
    };
    // Strict integer pieces (Python's int() rejects trailing junk like
    // "1a"; std::stol alone would parse the leading digits).
    auto strict_long = [](const std::string& v, long& out) -> bool {
        try {
            size_t used = 0;
            out = std::stol(v, &used);
            return used == v.size();
        } catch (...) {
            return false;
        }
    };
    auto add_counts_ranges = [&](const std::string& part, std::vector<uint8_t>& t) -> bool {
        size_t start = 0;
        while (start <= part.size()) {
            size_t plus = part.find('+', start);
            std::string piece = part.substr(
                start, plus == std::string::npos ? std::string::npos : plus - start);
            if (!piece.empty()) {
                long lo, hi;
                size_t dash = piece.find('-');
                if (dash == std::string::npos) {
                    if (!strict_long(piece, lo)) return false;
                    hi = lo;
                } else {
                    if (!strict_long(piece.substr(0, dash), lo) ||
                        !strict_long(piece.substr(dash + 1), hi))
                        return false;
                }
                if (lo < 0 || hi >= (long)t.size() || lo > hi) return false;
                for (long c = lo; c <= hi; ++c) t[(size_t)c] = 1;
            }
            if (plus == std::string::npos) break;
            start = plus + 1;
        }
        return true;
    };

    if (!s.empty() && s[0] == 'b' && s.find("/s") != std::string::npos) {
        out.radius = 1;
        out.birth.assign(9, 0);
        out.survive.assign(9, 0);
        size_t cut = s.find("/s");
        if (!add_counts_digits(s.substr(1, cut - 1), out.birth) ||
            !add_counts_digits(s.substr(cut + 2), out.survive)) {
            err = "rule '" + s + "': count out of range [0, 8] for radius 1";
            return false;
        }
        return true;
    }
    if (!s.empty() && s[0] == 'r' && s.find(",b") != std::string::npos) {
        size_t c1 = s.find(',');
        size_t c2 = s.find(',', c1 + 1);
        if (c2 == std::string::npos || s[c1 + 1] != 'b' || s[c2 + 1] != 's') {
            err = "cannot parse rule string '" + s + "'";
            return false;
        }
        long radius;
        try {
            radius = std::stol(s.substr(1, c1 - 1));
        } catch (...) {
            err = "cannot parse rule string '" + s + "'";
            return false;
        }
        if (radius < 1 || radius > 7) {  // uint8 count accumulators (rules.py)
            err = "radius must be in 1..7, got " + std::to_string(radius);
            return false;
        }
        int side = 2 * (int)radius + 1;
        size_t n = (size_t)(side * side);  // counts 0 .. (2r+1)^2 - 1
        out.radius = (int)radius;
        out.birth.assign(n, 0);
        out.survive.assign(n, 0);
        if (!add_counts_ranges(s.substr(c1 + 2, c2 - c1 - 2), out.birth) ||
            !add_counts_ranges(s.substr(c2 + 2), out.survive)) {
            err = "rule '" + s + "': count out of range [0, " +
                  std::to_string(n - 1) + "] for radius " + std::to_string(radius);
            return false;
        }
        return true;
    }
    err = "unknown rule '" + s +
          "'; built-ins: bosco daynight highlife life seeds; or use "
          "'B3/S23' / 'R5,B34-45,S33-57' syntax";
    return false;
}

std::string timestamp_name() {
    char buf[64];
    time_t raw;
    time(&raw);
    strftime(buf, sizeof(buf), "%Y-%m-%d-%H-%M-%S", localtime(&raw));
    return buf;
}

// One tile per worker with inclusive global coordinates, pid row-major in
// the tile mesh — byte-identical to golio.write_tile (trailing tab per
// row), and the same tiling the Python cpp-par path dumps.
void write_tiles(const std::string& dir, const std::string& name, int iter,
                 const uint8_t* grid, int64_t rows, int64_t cols,
                 int ti, int tj) {
    const int64_t tr = rows / ti, tc = cols / tj;
    for (int i = 0; i < ti; ++i) {
        for (int j = 0; j < tj; ++j) {
            int pid = i * tj + j;
            int64_t r0 = i * tr, c0 = j * tc;
            std::ofstream f(dir + "/" + name + "_" + std::to_string(iter) +
                            "_" + std::to_string(pid) + ".gol");
            f << r0 << " " << r0 + tr - 1 << "\n"
              << c0 << " " << c0 + tc - 1 << "\n";
            for (int64_t k = 0; k < tr; ++k) {
                const uint8_t* row = grid + (r0 + k) * cols + c0;
                for (int64_t l = 0; l < tc; ++l)
                    f << (row[l] ? "1" : "0") << "\t";
                f << "\n";
            }
        }
    }
}

void usage(const char* argv0) {
    std::fprintf(stderr,
        "usage: %s rows cols iteration_gap iterations [time_file] [first]\n"
        "       [--workers N] [--boundary periodic|dead] [--rule NAME]\n"
        "       [--seed S] [--save] [--out-dir D] [--name N]\n"
        "rules: life|highlife|seeds|daynight|bosco, or B3/S23 /\n"
        "       R5,B34-45,S33-57 syntax (radius 1..7)\n",
        argv0);
}

}  // namespace

int main(int argc, char** argv) {
    std::vector<std::string> pos;
    int workers = 1;
    std::string boundary = "periodic", rule_name = "life", out_dir = ".", name;
    uint32_t seed = 0;
    bool save = false;

    for (int i = 1; i < argc; ++i) {
        std::string a = argv[i];
        auto next = [&](const char* flag) -> std::string {
            if (i + 1 >= argc) {
                std::fprintf(stderr, "%s needs a value\n", flag);
                exit(2);
            }
            return argv[++i];
        };
        auto parse_int = [&](const char* flag, const std::string& v,
                             long lo, long hi) -> long {
            try {
                size_t used = 0;
                long out = std::stol(v, &used);
                if (used != v.size()) throw std::invalid_argument(v);
                if (out < lo || out > hi) throw std::out_of_range(v);
                return out;
            } catch (const std::exception&) {
                std::fprintf(stderr, "%s: invalid integer '%s' (range %ld..%ld)\n",
                             flag, v.c_str(), lo, hi);
                exit(2);
            }
        };
        if (a == "--workers")
            workers = (int)parse_int("--workers", next("--workers"), 1, INT_MAX);
        else if (a == "--boundary") boundary = next("--boundary");
        else if (a == "--rule") rule_name = next("--rule");
        else if (a == "--seed")
            seed = (uint32_t)parse_int("--seed", next("--seed"), 0, (long)UINT32_MAX);
        else if (a == "--out-dir") out_dir = next("--out-dir");
        else if (a == "--name") name = next("--name");
        else if (a == "--save") save = true;
        else if (a == "--help" || a == "-h") { usage(argv[0]); return 0; }
        else pos.push_back(a);
    }
    if (pos.size() < 4 || pos.size() > 6) {
        usage(argv[0]);
        return 2;
    }
    int64_t rows, cols, gap, iters;
    int first = 0;
    std::string time_file;
    try {
        rows = std::stoll(pos[0]);
        cols = std::stoll(pos[1]);
        gap = std::stoll(pos[2]);
        iters = std::stoll(pos[3]);
        if (pos.size() > 4) time_file = pos[4];
        if (pos.size() > 5) first = std::stoi(pos[5]);
    } catch (...) {
        std::fprintf(stderr, "One or more program arguments are invalid!\n");
        return 2;
    }
    if (rows <= 0 || cols <= 0 || iters < 0 || gap < 0) {
        std::fprintf(stderr, "Illegal board size parameter combination!\n");
        return 2;
    }
    ParsedRule rule;
    std::string rule_err;
    if (!parse_rule(rule_name, rule, rule_err)) {
        std::fprintf(stderr, "%s\n", rule_err.c_str());
        return 2;
    }
    if (boundary != "periodic" && boundary != "dead") {
        std::fprintf(stderr, "boundary must be periodic|dead\n");
        return 2;
    }
    int periodic = boundary == "periodic" ? 1 : 0;
    if (name.empty()) name = timestamp_name();
    if (time_file.empty()) time_file = name;

    auto t_begin = std::chrono::steady_clock::now();

    std::vector<uint8_t> grid((size_t)(rows * cols));
    gol_init(grid.data(), rows, cols, seed, 0, 0);

    // worker-tile mesh: most-square factorization, shrinking the worker
    // count until the mesh divides the grid into tiles that can source a
    // radius-deep ghost slab (same policy as the Python bindings'
    // plan_tiles); warn when degraded below the request.
    int requested = workers;
    int ti = 1, tj = 1;
    for (int w = workers; w >= 1; --w) {
        int a_best = 1;
        for (int a = 1; (int64_t)a * a <= w; ++a)
            if (w % a == 0) a_best = a;
        int b = w / a_best;
        if (rows % a_best == 0 && cols % b == 0 &&
            rows / a_best >= rule.radius && cols / b >= rule.radius) {
            ti = a_best; tj = b;
            break;
        }
    }
    if (ti * tj != requested)
        std::fprintf(stderr,
                     "gol_native: %d workers requested, using %dx%d=%d "
                     "(mesh must divide the grid)\n",
                     requested, ti, tj, ti * tj);

    // master manifest (one writer process; processes = tile writers)
    {
        std::ofstream f(out_dir + "/" + name + ".gol");
        f << rows << " " << cols << " " << gap << " " << iters << " "
          << ti * tj << "\n";
    }
    if (save) write_tiles(out_dir, name, 0, grid.data(), rows, cols, ti, tj);

    auto t_setup = std::chrono::steady_clock::now();

    int64_t done = 0;
    while (done < iters) {
        int64_t n = (save && gap > 0) ? std::min(gap, iters - done) : iters - done;
        int rc = 0;
        if (ti * tj > 1)
            rc = gol_evolve_par(grid.data(), rows, cols, n, rule.birth.data(),
                                rule.survive.data(), rule.radius, periodic,
                                ti, tj);
        else
            gol_evolve(grid.data(), rows, cols, n, rule.birth.data(),
                       rule.survive.data(), rule.radius, periodic);
        if (rc != 0) {
            std::fprintf(stderr, "engine rejected %dx%d tile mesh (rc=%d)\n",
                         ti, tj, rc);
            return 1;
        }
        done += n;
        if (save)
            write_tiles(out_dir, name, (int)done, grid.data(), rows, cols,
                        ti, tj);
    }

    auto t_end = std::chrono::steady_clock::now();
    using us = std::chrono::microseconds;
    long full = std::chrono::duration_cast<us>(t_end - t_begin).count();
    long setup = std::chrono::duration_cast<us>(t_setup - t_begin).count();
    long nosetup = full - setup;
    int p = ti * tj;

    std::ofstream csv(out_dir + "/" + time_file + "_compact.csv", std::ios::app);
    if (first != 0)
        csv << "X,Y,#P,full single,full avg,full sum,nosetup single,nosetup avg,"
               "nosetup sum,setup single ,setup avg ,setup sum \n";
    csv << rows << "," << cols << "," << p << "," << full << "," << full << ","
        << full * p << "," << nosetup << "," << nosetup << "," << nosetup * p
        << "," << setup << "," << setup << "," << setup * p << "\n";

    long pop = 0;
    for (uint8_t v : grid) pop += v;
    std::printf("gol_native %s: %lldx%lld x%lld steps, %d workers, "
                "%.3f Gcells/s, population %ld\n",
                name.c_str(), (long long)rows, (long long)cols,
                (long long)iters, p,
                nosetup > 0 ? (double)rows * cols * iters / nosetup / 1e3 : 0.0,
                pop);
    return 0;
}
