// gol_native — standalone native CLI, runnable without Python.
//
// The reference ships two standalone binaries (./gol via mpirun and
// ./gol_serial); this is the framework's equivalent front end over the
// golcore engine: same positional contract
//     rows cols iteration_gap iterations [time_file] [first]
// (reference main.cpp:171-223) plus flags for what the reference
// hardcoded: --workers N (multi-worker tile engine; the mpirun -np
// analog), --boundary periodic|dead, --rule life|highlife|seeds|daynight,
// --seed S, --save, --out-dir D, --name N.
//
// Emits the same .gol master/tile format as the Python CLI (golio.py), so
// tools/gol_visualization.py and the parity tests consume its dumps
// directly, and appends the reference-schema 12-column timing CSV
// (main.cpp:356-363) with correctly-labeled microseconds.

#include <chrono>
#include <climits>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <fstream>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void gol_init(uint8_t*, int64_t, int64_t, uint32_t, int64_t, int64_t);
void gol_evolve(uint8_t*, int64_t, int64_t, int64_t, const uint8_t*,
                const uint8_t*, int, int);
int gol_evolve_par(uint8_t*, int64_t, int64_t, int64_t, const uint8_t*,
                   const uint8_t*, int, int, int, int);
}

namespace {

struct Rule {
    const char* name;
    uint8_t birth[9];
    uint8_t survive[9];
};

// radius-1 built-ins (tables indexed by neighbor count 0..8)
const Rule kRules[] = {
    {"life",     {0,0,0,1,0,0,0,0,0}, {0,0,1,1,0,0,0,0,0}},
    {"highlife", {0,0,0,1,0,0,1,0,0}, {0,0,1,1,0,0,0,0,0}},
    {"seeds",    {0,0,1,0,0,0,0,0,0}, {0,0,0,0,0,0,0,0,0}},
    {"daynight", {0,0,0,1,0,0,1,1,1}, {0,0,0,1,1,0,1,1,1}},
};

const Rule* find_rule(const std::string& n) {
    for (const auto& r : kRules)
        if (n == r.name) return &r;
    return nullptr;
}

std::string timestamp_name() {
    char buf[64];
    time_t raw;
    time(&raw);
    strftime(buf, sizeof(buf), "%Y-%m-%d-%H-%M-%S", localtime(&raw));
    return buf;
}

void write_tile(const std::string& dir, const std::string& name, int iter,
                const uint8_t* grid, int64_t rows, int64_t cols) {
    std::ofstream f(dir + "/" + name + "_" + std::to_string(iter) + "_0.gol");
    f << 0 << " " << rows - 1 << "\n" << 0 << " " << cols - 1 << "\n";
    for (int64_t i = 0; i < rows; ++i) {
        for (int64_t j = 0; j < cols; ++j)
            f << (grid[i * cols + j] ? "1" : "0") << "\t";
        f << "\n";
    }
}

void usage(const char* argv0) {
    std::fprintf(stderr,
        "usage: %s rows cols iteration_gap iterations [time_file] [first]\n"
        "       [--workers N] [--boundary periodic|dead] [--rule NAME]\n"
        "       [--seed S] [--save] [--out-dir D] [--name N]\n",
        argv0);
}

}  // namespace

int main(int argc, char** argv) {
    std::vector<std::string> pos;
    int workers = 1;
    std::string boundary = "periodic", rule_name = "life", out_dir = ".", name;
    uint32_t seed = 0;
    bool save = false;

    for (int i = 1; i < argc; ++i) {
        std::string a = argv[i];
        auto next = [&](const char* flag) -> std::string {
            if (i + 1 >= argc) {
                std::fprintf(stderr, "%s needs a value\n", flag);
                exit(2);
            }
            return argv[++i];
        };
        auto parse_int = [&](const char* flag, const std::string& v,
                             long lo, long hi) -> long {
            try {
                size_t used = 0;
                long out = std::stol(v, &used);
                if (used != v.size()) throw std::invalid_argument(v);
                if (out < lo || out > hi) throw std::out_of_range(v);
                return out;
            } catch (const std::exception&) {
                std::fprintf(stderr, "%s: invalid integer '%s' (range %ld..%ld)\n",
                             flag, v.c_str(), lo, hi);
                exit(2);
            }
        };
        if (a == "--workers")
            workers = (int)parse_int("--workers", next("--workers"), 1, INT_MAX);
        else if (a == "--boundary") boundary = next("--boundary");
        else if (a == "--rule") rule_name = next("--rule");
        else if (a == "--seed")
            seed = (uint32_t)parse_int("--seed", next("--seed"), 0, (long)UINT32_MAX);
        else if (a == "--out-dir") out_dir = next("--out-dir");
        else if (a == "--name") name = next("--name");
        else if (a == "--save") save = true;
        else if (a == "--help" || a == "-h") { usage(argv[0]); return 0; }
        else pos.push_back(a);
    }
    if (pos.size() < 4 || pos.size() > 6) {
        usage(argv[0]);
        return 2;
    }
    int64_t rows, cols, gap, iters;
    int first = 0;
    std::string time_file;
    try {
        rows = std::stoll(pos[0]);
        cols = std::stoll(pos[1]);
        gap = std::stoll(pos[2]);
        iters = std::stoll(pos[3]);
        if (pos.size() > 4) time_file = pos[4];
        if (pos.size() > 5) first = std::stoi(pos[5]);
    } catch (...) {
        std::fprintf(stderr, "One or more program arguments are invalid!\n");
        return 2;
    }
    if (rows <= 0 || cols <= 0 || iters < 0 || gap < 0) {
        std::fprintf(stderr, "Illegal board size parameter combination!\n");
        return 2;
    }
    const Rule* rule = find_rule(rule_name);
    if (!rule) {
        std::fprintf(stderr, "unknown rule '%s'\n", rule_name.c_str());
        return 2;
    }
    if (boundary != "periodic" && boundary != "dead") {
        std::fprintf(stderr, "boundary must be periodic|dead\n");
        return 2;
    }
    int periodic = boundary == "periodic" ? 1 : 0;
    if (name.empty()) name = timestamp_name();
    if (time_file.empty()) time_file = name;

    auto t_begin = std::chrono::steady_clock::now();

    std::vector<uint8_t> grid((size_t)(rows * cols));
    gol_init(grid.data(), rows, cols, seed, 0, 0);

    // worker-tile mesh: most-square factorization, shrinking the worker
    // count until the mesh divides the grid (same policy as the Python
    // bindings' plan_tiles); warn when degraded below the request.
    int requested = workers;
    int ti = 1, tj = 1;
    for (int w = workers; w >= 1; --w) {
        int a_best = 1;
        for (int a = 1; (int64_t)a * a <= w; ++a)
            if (w % a == 0) a_best = a;
        int b = w / a_best;
        if (rows % a_best == 0 && cols % b == 0 && rows / a_best >= 1 &&
            cols / b >= 1) {
            ti = a_best; tj = b;
            break;
        }
    }
    if (ti * tj != requested)
        std::fprintf(stderr,
                     "gol_native: %d workers requested, using %dx%d=%d "
                     "(mesh must divide the grid)\n",
                     requested, ti, tj, ti * tj);

    // master manifest (one writer process)
    {
        std::ofstream f(out_dir + "/" + name + ".gol");
        f << rows << " " << cols << " " << gap << " " << iters << " " << 1 << "\n";
    }
    if (save) write_tile(out_dir, name, 0, grid.data(), rows, cols);

    auto t_setup = std::chrono::steady_clock::now();

    int64_t done = 0;
    while (done < iters) {
        int64_t n = (save && gap > 0) ? std::min(gap, iters - done) : iters - done;
        int rc = 0;
        if (ti * tj > 1)
            rc = gol_evolve_par(grid.data(), rows, cols, n, rule->birth,
                                rule->survive, 1, periodic, ti, tj);
        else
            gol_evolve(grid.data(), rows, cols, n, rule->birth, rule->survive,
                       1, periodic);
        if (rc != 0) {
            std::fprintf(stderr, "engine rejected %dx%d tile mesh (rc=%d)\n",
                         ti, tj, rc);
            return 1;
        }
        done += n;
        if (save) write_tile(out_dir, name, (int)done, grid.data(), rows, cols);
    }

    auto t_end = std::chrono::steady_clock::now();
    using us = std::chrono::microseconds;
    long full = std::chrono::duration_cast<us>(t_end - t_begin).count();
    long setup = std::chrono::duration_cast<us>(t_setup - t_begin).count();
    long nosetup = full - setup;
    int p = ti * tj;

    std::ofstream csv(out_dir + "/" + time_file + "_compact.csv", std::ios::app);
    if (first != 0)
        csv << "X,Y,#P,full single,full avg,full sum,nosetup single,nosetup avg,"
               "nosetup sum,setup single ,setup avg ,setup sum \n";
    csv << rows << "," << cols << "," << p << "," << full << "," << full << ","
        << full * p << "," << nosetup << "," << nosetup << "," << nosetup * p
        << "," << setup << "," << setup << "," << setup * p << "\n";

    long pop = 0;
    for (uint8_t v : grid) pop += v;
    std::printf("gol_native %s: %lldx%lld x%lld steps, %d workers, "
                "%.3f Gcells/s, population %ld\n",
                name.c_str(), (long long)rows, (long long)cols,
                (long long)iters, p,
                nosetup > 0 ? (double)rows * cols * iters / nosetup / 1e3 : 0.0,
                pop);
    return 0;
}
