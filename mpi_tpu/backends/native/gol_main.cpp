// gol_native — standalone native CLI, runnable without Python.
//
// The reference ships two standalone binaries (./gol via mpirun and
// ./gol_serial); this is the framework's equivalent front end over the
// golcore engine: same positional contract
//     rows cols iteration_gap iterations [time_file] [first]
// (reference main.cpp:171-223) plus flags for what the reference
// hardcoded: --workers N (multi-worker tile engine; the mpirun -np
// analog), --boundary periodic|dead, --rule NAME (built-ins plus the
// same 'B3/S23' / 'R5,B34-45,S33-57' grammar as models/rules.py, any
// radius 1..7), --seed S, --save, --out-dir D, --name N.
//
// Emits the same .gol master/tile format as the Python CLI (golio.py) —
// one tile per worker with global coordinates, like each MPI rank's own
// dump in the reference (main.cpp:106-129) — so
// tools/gol_visualization.py and the parity tests consume its output
// directly, and appends the reference-schema 12-column timing CSV
// (main.cpp:356-363) with correctly-labeled microseconds.

#include <cctype>
#include <chrono>
#include <climits>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <fstream>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void gol_init(uint8_t*, int64_t, int64_t, uint32_t, int64_t, int64_t);
void gol_evolve(uint8_t*, int64_t, int64_t, int64_t, const uint8_t*,
                const uint8_t*, int, int);
int gol_evolve_par_t(uint8_t*, int64_t, int64_t, int64_t, const uint8_t*,
                     const uint8_t*, int, int, int, int, int64_t*);
}

namespace {

// An outer-totalistic rule as the engine consumes it: count-indexed birth/
// survive tables of size (2r+1)^2 (the form models/rules.py `tables()`
// produces for the ctypes path — one grammar, two front ends).
struct ParsedRule {
    int radius = 1;
    std::vector<uint8_t> birth, survive;
};

// Built-ins route through the same string grammar as the Python registry
// (models/rules.py LIFE/HIGHLIFE/SEEDS/DAY_AND_NIGHT/BOSCO).
const char* builtin_rule(const std::string& n) {
    if (n == "life") return "b3/s23";
    if (n == "highlife") return "b36/s23";
    if (n == "seeds") return "b2/s";
    if (n == "daynight") return "b3678/s34678";
    if (n == "bosco") return "r5,b34-45,s33-57";
    return nullptr;
}

// "b<digits>/s<digits>" (radius 1) or "r<N>,b<ranges>,s<ranges>" where
// ranges are '+'-joined "lo-hi" / single counts — mirrors
// rules.rule_from_name exactly.  Returns false on parse/validation error.
bool parse_rule(std::string s, ParsedRule& out, std::string& err) {
    for (auto& c : s) c = (char)tolower(c);
    if (const char* b = builtin_rule(s)) s = b;

    // Non-digit characters are skipped (Python: `if ch.isdigit()`), but an
    // out-of-range digit errors (Python: Rule.__post_init__ range check) —
    // B9/S23 must fail the same way in both front ends.
    auto add_counts_digits = [](const std::string& part, std::vector<uint8_t>& t) -> bool {
        for (char c : part) {
            if (c < '0' || c > '9') continue;
            if ((size_t)(c - '0') >= t.size()) return false;
            t[(size_t)(c - '0')] = 1;
        }
        return true;
    };
    // Strict integer pieces (Python's int() rejects trailing junk like
    // "1a"; std::stol alone would parse the leading digits).
    auto strict_long = [](const std::string& v, long& out) -> bool {
        try {
            size_t used = 0;
            out = std::stol(v, &used);
            return used == v.size();
        } catch (...) {
            return false;
        }
    };
    auto add_counts_ranges = [&](const std::string& part, std::vector<uint8_t>& t) -> bool {
        size_t start = 0;
        while (start <= part.size()) {
            size_t plus = part.find('+', start);
            std::string piece = part.substr(
                start, plus == std::string::npos ? std::string::npos : plus - start);
            if (!piece.empty()) {
                long lo, hi;
                size_t dash = piece.find('-');
                if (dash == std::string::npos) {
                    if (!strict_long(piece, lo)) return false;
                    hi = lo;
                } else {
                    if (!strict_long(piece.substr(0, dash), lo) ||
                        !strict_long(piece.substr(dash + 1), hi))
                        return false;
                }
                if (lo < 0 || hi >= (long)t.size() || lo > hi) return false;
                for (long c = lo; c <= hi; ++c) t[(size_t)c] = 1;
            }
            if (plus == std::string::npos) break;
            start = plus + 1;
        }
        return true;
    };

    if (!s.empty() && s[0] == 'b' && s.find("/s") != std::string::npos) {
        out.radius = 1;
        out.birth.assign(9, 0);
        out.survive.assign(9, 0);
        size_t cut = s.find("/s");
        if (!add_counts_digits(s.substr(1, cut - 1), out.birth) ||
            !add_counts_digits(s.substr(cut + 2), out.survive)) {
            err = "rule '" + s + "': count out of range [0, 8] for radius 1";
            return false;
        }
        return true;
    }
    if (!s.empty() && s[0] == 'r' && s.find(",b") != std::string::npos) {
        size_t c1 = s.find(',');
        size_t c2 = s.find(',', c1 + 1);
        if (c2 == std::string::npos || s[c1 + 1] != 'b' || s[c2 + 1] != 's') {
            err = "cannot parse rule string '" + s + "'";
            return false;
        }
        long radius;
        try {
            radius = std::stol(s.substr(1, c1 - 1));
        } catch (...) {
            err = "cannot parse rule string '" + s + "'";
            return false;
        }
        if (radius < 1 || radius > 7) {  // uint8 count accumulators (rules.py)
            err = "radius must be in 1..7, got " + std::to_string(radius);
            return false;
        }
        int side = 2 * (int)radius + 1;
        size_t n = (size_t)(side * side);  // counts 0 .. (2r+1)^2 - 1
        out.radius = (int)radius;
        out.birth.assign(n, 0);
        out.survive.assign(n, 0);
        if (!add_counts_ranges(s.substr(c1 + 2, c2 - c1 - 2), out.birth) ||
            !add_counts_ranges(s.substr(c2 + 2), out.survive)) {
            err = "rule '" + s + "': count out of range [0, " +
                  std::to_string(n - 1) + "] for radius " + std::to_string(radius);
            return false;
        }
        return true;
    }
    err = "unknown rule '" + s +
          "'; built-ins: bosco daynight highlife life seeds; or use "
          "'B3/S23' / 'R5,B34-45,S33-57' syntax";
    return false;
}

std::string timestamp_name() {
    char buf[64];
    time_t raw;
    time(&raw);
    strftime(buf, sizeof(buf), "%Y-%m-%d-%H-%M-%S", localtime(&raw));
    return buf;
}

// .golp packed-binary tile constants — wire format shared with golio.py
// (write_tile_packed: magic + two coordinate lines + MSB-first packbits
// rows, each row padded to a whole byte).
const char kGolpMagic[] = "GOLP1\n";
const int64_t kGolpThreshold = 1 << 24;  // auto: text at/below, packed above

// One tile per worker with inclusive global coordinates, pid row-major in
// the tile mesh — byte-identical to golio.write_tile (trailing tab per
// row), and the same tiling the Python cpp-par path dumps.  fmt selects
// "gol" text / "golp" packed / "auto" (packed above kGolpThreshold cells);
// the other format's file for the same pid is removed so rewrites leave
// one canonical tile (golio.write_tile_fmt's discipline).
void write_tiles(const std::string& dir, const std::string& name, long iter,
                 const uint8_t* grid, int64_t rows, int64_t cols,
                 int ti, int tj, const std::string& fmt) {
    const int64_t tr = rows / ti, tc = cols / tj;
    const bool packed = fmt == "golp" || (fmt == "auto" && tr * tc > kGolpThreshold);
    for (int i = 0; i < ti; ++i) {
        for (int j = 0; j < tj; ++j) {
            int pid = i * tj + j;
            int64_t r0 = i * tr, c0 = j * tc;
            std::string base = dir + "/" + name + "_" + std::to_string(iter) +
                               "_" + std::to_string(pid);
            if (packed) {
                std::ofstream f(base + ".golp", std::ios::binary);
                f << kGolpMagic
                  << r0 << " " << r0 + tr - 1 << "\n"
                  << c0 << " " << c0 + tc - 1 << "\n";
                const int64_t rb = (tc + 7) / 8;
                std::vector<uint8_t> rowbuf((size_t)rb);
                for (int64_t k = 0; k < tr; ++k) {
                    const uint8_t* row = grid + (r0 + k) * cols + c0;
                    std::memset(rowbuf.data(), 0, (size_t)rb);
                    for (int64_t l = 0; l < tc; ++l)
                        if (row[l]) rowbuf[(size_t)(l >> 3)] |= 0x80u >> (l & 7);
                    f.write((const char*)rowbuf.data(), rb);
                }
                std::remove((base + ".gol").c_str());
            } else {
                std::ofstream f(base + ".gol");
                f << r0 << " " << r0 + tr - 1 << "\n"
                  << c0 << " " << c0 + tc - 1 << "\n";
                for (int64_t k = 0; k < tr; ++k) {
                    const uint8_t* row = grid + (r0 + k) * cols + c0;
                    for (int64_t l = 0; l < tc; ++l)
                        f << (row[l] ? "1" : "0") << "\t";
                    f << "\n";
                }
                std::remove((base + ".golp").c_str());
            }
        }
    }
    // Prune stale higher-pid tiles left by an earlier wider run at this
    // iteration (golio.remove_stale_tiles' discipline): without this, a
    // rewrite with fewer workers leaves old tiles that resume/assemble
    // would silently mix in.  Every run writes contiguous pids 0..P-1,
    // so scanning upward from this run's count until a gap is complete.
    for (int pid = ti * tj;; ++pid) {
        std::string base = dir + "/" + name + "_" + std::to_string(iter) +
                           "_" + std::to_string(pid);
        bool had_text = std::remove((base + ".gol").c_str()) == 0;
        bool had_packed = std::remove((base + ".golp").c_str()) == 0;
        if (!had_text && !had_packed) break;
    }
}

// Read one snapshot tile (either format) into the global grid; returns
// 0 = no file for this pid, 1 = loaded, -1 = malformed (err set).
int read_tile_into(const std::string& dir, const std::string& name, long iter,
                   int pid, uint8_t* grid, int64_t rows, int64_t cols,
                   std::string& err) {
    std::string base = dir + "/" + name + "_" + std::to_string(iter) + "_" +
                       std::to_string(pid);
    auto fail = [&](const std::string& m) {
        err = base + ": " + m;
        return -1;
    };
    std::ifstream pf(base + ".golp", std::ios::binary);
    if (pf) {
        std::string magic(sizeof(kGolpMagic) - 1, '\0');
        pf.read(&magic[0], (std::streamsize)magic.size());
        if (!pf || magic != kGolpMagic) return fail("bad .golp magic");
        int64_t r0, r1, c0, c1;
        pf >> r0 >> r1 >> c0 >> c1;
        if (!pf) return fail("bad .golp header");
        pf.ignore(1);  // the newline after the second coordinate line
        if (r0 < 0 || r1 >= rows || c0 < 0 || c1 >= cols || r0 > r1 || c0 > c1)
            return fail("tile outside grid");
        const int64_t tr = r1 - r0 + 1, tc = c1 - c0 + 1;
        const int64_t rb = (tc + 7) / 8;
        std::vector<uint8_t> rowbuf((size_t)rb);
        for (int64_t k = 0; k < tr; ++k) {
            pf.read((char*)rowbuf.data(), rb);
            if (!pf) return fail("truncated .golp body");
            uint8_t* row = grid + (r0 + k) * cols + c0;
            for (int64_t l = 0; l < tc; ++l)
                row[l] = (rowbuf[(size_t)(l >> 3)] >> (7 - (l & 7))) & 1u;
        }
        return 1;
    }
    std::ifstream tf(base + ".gol");
    if (!tf) return 0;
    int64_t r0, r1, c0, c1;
    tf >> r0 >> r1 >> c0 >> c1;
    if (!tf) return fail("bad .gol header");
    if (r0 < 0 || r1 >= rows || c0 < 0 || c1 >= cols || r0 > r1 || c0 > c1)
        return fail("tile outside grid");
    for (int64_t k = 0; k <= r1 - r0; ++k) {
        uint8_t* row = grid + (r0 + k) * cols + c0;
        for (int64_t l = 0; l <= c1 - c0; ++l) {
            int v;
            if (!(tf >> v) || (v != 0 && v != 1))
                return fail("malformed .gol body");
            row[l] = (uint8_t)v;
        }
    }
    return 1;
}

void usage(const char* argv0) {
    std::fprintf(stderr,
        "usage: %s rows cols iteration_gap iterations [time_file] [first]\n"
        "       [--workers N] [--boundary periodic|dead] [--rule NAME]\n"
        "       [--seed S] [--save] [--out-dir D] [--name N] [--strict]\n"
        "       [--resume NAME@ITER] [--snapshot-format auto|gol|golp]\n"
        "rules: life|highlife|seeds|daynight|bosco, or B3/S23 /\n"
        "       R5,B34-45,S33-57 syntax (radius 1..7)\n",
        argv0);
}

}  // namespace

int main(int argc, char** argv) {
    std::vector<std::string> pos;
    int workers = 1;
    std::string boundary = "periodic", rule_name = "life", out_dir = ".", name;
    std::string resume, snap_fmt = "auto";
    uint32_t seed = 0;
    bool save = false, strict = false;

    for (int i = 1; i < argc; ++i) {
        std::string a = argv[i];
        auto next = [&](const char* flag) -> std::string {
            if (i + 1 >= argc) {
                std::fprintf(stderr, "%s needs a value\n", flag);
                exit(2);
            }
            return argv[++i];
        };
        auto parse_int = [&](const char* flag, const std::string& v,
                             long lo, long hi) -> long {
            try {
                size_t used = 0;
                long out = std::stol(v, &used);
                if (used != v.size()) throw std::invalid_argument(v);
                if (out < lo || out > hi) throw std::out_of_range(v);
                return out;
            } catch (const std::exception&) {
                std::fprintf(stderr, "%s: invalid integer '%s' (range %ld..%ld)\n",
                             flag, v.c_str(), lo, hi);
                exit(2);
            }
        };
        if (a == "--workers")
            workers = (int)parse_int("--workers", next("--workers"), 1, INT_MAX);
        else if (a == "--boundary") boundary = next("--boundary");
        else if (a == "--rule") rule_name = next("--rule");
        else if (a == "--seed")
            seed = (uint32_t)parse_int("--seed", next("--seed"), 0, (long)UINT32_MAX);
        else if (a == "--out-dir") out_dir = next("--out-dir");
        else if (a == "--name") name = next("--name");
        else if (a == "--save") save = true;
        else if (a == "--strict") strict = true;
        else if (a == "--resume") resume = next("--resume");
        else if (a == "--snapshot-format") snap_fmt = next("--snapshot-format");
        else if (a == "--help" || a == "-h") { usage(argv[0]); return 0; }
        else pos.push_back(a);
    }
    if (pos.size() < 4 || pos.size() > 6) {
        usage(argv[0]);
        return 2;
    }
    int64_t rows, cols, gap, iters;
    int first = 0;
    std::string time_file;
    try {
        rows = std::stoll(pos[0]);
        cols = std::stoll(pos[1]);
        gap = std::stoll(pos[2]);
        iters = std::stoll(pos[3]);
        if (pos.size() > 4) time_file = pos[4];
        if (pos.size() > 5) first = std::stoi(pos[5]);
    } catch (...) {
        std::fprintf(stderr, "One or more program arguments are invalid!\n");
        return 2;
    }
    if (rows <= 0 || cols <= 0 || iters < 0 || gap < 0) {
        std::fprintf(stderr, "Illegal board size parameter combination!\n");
        return 2;
    }
    ParsedRule rule;
    std::string rule_err;
    if (!parse_rule(rule_name, rule, rule_err)) {
        std::fprintf(stderr, "%s\n", rule_err.c_str());
        return 2;
    }
    if (boundary != "periodic" && boundary != "dead") {
        std::fprintf(stderr, "boundary must be periodic|dead\n");
        return 2;
    }
    int periodic = boundary == "periodic" ? 1 : 0;
    if (snap_fmt != "auto" && snap_fmt != "gol" && snap_fmt != "golp") {
        std::fprintf(stderr, "--snapshot-format must be auto|gol|golp\n");
        return 2;
    }

    // --resume NAME@ITER (Python cli.py's contract): master header must
    // match the requested grid; 'iterations' counts additional steps.
    std::string resume_name;
    long start_iter = 0;
    if (!resume.empty()) {
        size_t at = resume.rfind('@');
        if (at == std::string::npos) {
            std::fprintf(stderr, "--resume must look like NAME@ITER, got '%s'\n",
                         resume.c_str());
            return 2;
        }
        resume_name = resume.substr(0, at);
        try {
            start_iter = std::stol(resume.substr(at + 1));
        } catch (...) {
            std::fprintf(stderr, "--resume must look like NAME@ITER, got '%s'\n",
                         resume.c_str());
            return 2;
        }
        std::ifstream mf(out_dir + "/" + resume_name + ".gol");
        int64_t srows, scols;
        long sgap, siters, sprocs;
        if (!mf || !(mf >> srows >> scols >> sgap >> siters >> sprocs)) {
            std::fprintf(stderr, "cannot resume '%s': no readable master %s.gol\n",
                         resume.c_str(), resume_name.c_str());
            return 2;
        }
        if (srows != rows || scols != cols) {
            std::fprintf(stderr,
                         "snapshot %s@%ld is %lldx%lld, run asks for %lldx%lld\n",
                         resume_name.c_str(), start_iter, (long long)srows,
                         (long long)scols, (long long)rows, (long long)cols);
            return 2;
        }
        if (name.empty()) name = resume_name;
    }
    if (name.empty()) name = timestamp_name();
    if (time_file.empty()) time_file = name;

    auto t_begin = std::chrono::steady_clock::now();

    std::vector<uint8_t> grid((size_t)(rows * cols));
    if (!resume_name.empty()) {
        // load every pid's tile (contiguous pids 0..N-1, both formats)
        std::fill(grid.begin(), grid.end(), 2);  // 2 = unseen sentinel
        std::string terr;
        int pid = 0;
        for (;; ++pid) {
            int rc = read_tile_into(out_dir, resume_name, start_iter, pid,
                                    grid.data(), rows, cols, terr);
            if (rc < 0) {
                std::fprintf(stderr, "cannot resume: %s\n", terr.c_str());
                return 2;
            }
            if (rc == 0) break;
        }
        if (pid == 0) {
            std::fprintf(stderr, "cannot resume '%s': no tile files at "
                         "iteration %ld\n", resume.c_str(), start_iter);
            return 2;
        }
        for (uint8_t v : grid)
            if (v > 1) {
                std::fprintf(stderr, "cannot resume '%s': tiles do not cover "
                             "the grid\n", resume.c_str());
                return 2;
            }
    } else {
        gol_init(grid.data(), rows, cols, seed, 0, 0);
    }

    // worker-tile mesh: most-square factorization, shrinking the worker
    // count until the mesh divides the grid into tiles that can source a
    // radius-deep ghost slab (same policy as the Python bindings'
    // plan_tiles); warn when degraded below the request.
    int requested = workers;
    int ti = 1, tj = 1;
    for (int w = workers; w >= 1; --w) {
        int a_best = 1;
        for (int a = 1; (int64_t)a * a <= w; ++a)
            if (w % a == 0) a_best = a;
        int b = w / a_best;
        if (rows % a_best == 0 && cols % b == 0 &&
            rows / a_best >= rule.radius && cols / b >= rule.radius) {
            ti = a_best; tj = b;
            break;
        }
    }
    if (ti * tj != requested)
        std::fprintf(stderr,
                     "gol_native: %d workers requested, using %dx%d=%d "
                     "(mesh must divide the grid)\n",
                     requested, ti, tj, ti * tj);

    // --strict: the reference's exact preconditions (main.cpp:195), judged
    // against the EFFECTIVE decomposition like config.validate_strict
    if (strict) {
        if (rows != cols) {
            std::fprintf(stderr, "strict mode: grid must be square\n");
            return 2;
        }
        if (ti != tj) {
            std::fprintf(stderr,
                         "strict mode: worker count must be a perfect square "
                         "mesh (effective mesh %dx%d)\n", ti, tj);
            return 2;
        }
        if (rows / ti < 4) {
            std::fprintf(stderr,
                         "strict mode: tile must be >= 4 cells per side\n");
            return 2;
        }
    }

    // master manifest (one writer process; processes = tile writers);
    // resumed runs extend the iteration count
    {
        std::ofstream f(out_dir + "/" + name + ".gol");
        f << rows << " " << cols << " " << gap << " " << iters + start_iter
          << " " << ti * tj << "\n";
    }
    if (save && start_iter == 0)
        write_tiles(out_dir, name, 0, grid.data(), rows, cols, ti, tj, snap_fmt);

    auto t_setup = std::chrono::steady_clock::now();

    std::vector<int64_t> worker_us((size_t)(ti * tj), 0);
    int64_t done = 0;
    while (done < iters) {
        int64_t n = (save && gap > 0) ? std::min(gap, iters - done) : iters - done;
        int rc = 0;
        if (ti * tj > 1)
            rc = gol_evolve_par_t(grid.data(), rows, cols, n, rule.birth.data(),
                                  rule.survive.data(), rule.radius, periodic,
                                  ti, tj, worker_us.data());
        else
            gol_evolve(grid.data(), rows, cols, n, rule.birth.data(),
                       rule.survive.data(), rule.radius, periodic);
        if (rc != 0) {
            std::fprintf(stderr, "engine rejected %dx%d tile mesh (rc=%d)\n",
                         ti, tj, rc);
            return 1;
        }
        done += n;
        if (save)
            write_tiles(out_dir, name, start_iter + done, grid.data(), rows,
                        cols, ti, tj, snap_fmt);
    }

    auto t_end = std::chrono::steady_clock::now();
    using us = std::chrono::microseconds;
    long full = std::chrono::duration_cast<us>(t_end - t_begin).count();
    long setup = std::chrono::duration_cast<us>(t_setup - t_begin).count();
    long nosetup = full - setup;
    int p = ti * tj;

    // avg/sum columns from MEASURED per-worker durations when the
    // threaded engine ran (the reference's three MPI_Reduce of per-rank
    // times, main.cpp:319-324); single = the main thread's wall time
    // (rank-0 analog).  Workers exist only inside the evolve loop, so
    // their full time is setup (shared, program-wide) + measured nosetup.
    long nos_avg = nosetup, nos_sum = nosetup * p;
    {
        // avg over the slots that actually accumulated time: the engine
        // may run fewer threads than p (w is capped at the row count and
        // the blocked engine credits only w slots), and averaging over
        // idle slots would under-report per-worker time relative to the
        // reference's per-rank MPI_Reduce semantics (main.cpp:319-324)
        int64_t sum = 0;
        int active = 0;
        for (int64_t v : worker_us) {
            sum += v;
            if (v > 0) ++active;
        }
        if (sum > 0 && active > 0) {
            nos_avg = (long)(sum / active);
            nos_sum = (long)sum;
        }
        // NB: when active < p the avg and sum columns describe the active
        // workers while #P stays the decomposition (tile-writer count), so
        // avg * #P deliberately over-reconstructs sum — #P is the wire
        // contract (reference CSV schema), not the thread count.
    }
    long full_avg = setup + nos_avg, full_sum = (long)setup * p + nos_sum;

    std::ofstream csv(out_dir + "/" + time_file + "_compact.csv", std::ios::app);
    if (first != 0)
        csv << "X,Y,#P,full single,full avg,full sum,nosetup single,nosetup avg,"
               "nosetup sum,setup single ,setup avg ,setup sum \n";
    csv << rows << "," << cols << "," << p << "," << full << "," << full_avg
        << "," << full_sum << "," << nosetup << "," << nos_avg << ","
        << nos_sum << "," << setup << "," << setup << "," << setup * p << "\n";

    // human-readable report, same layout as utils/timing.py write_reports
    // (the reference emits both, main.cpp:333-353; VERDICT r2 missing #2)
    {
        std::ofstream det(out_dir + "/" + time_file + "_detailed.out",
                          std::ios::app);
        det << "Timing results: microseconds\n"
            << "size:" << rows << " by " << cols << "\n"
            << p << " Processors\n";
        const char* labels[3] = {"Full (with setup)", "Without setup", "Setup"};
        long singles[3] = {full, nosetup, setup};
        long avgs[3] = {full_avg, nos_avg, setup};
        long sums[3] = {full_sum, nos_sum, (long)setup * p};
        for (int k = 0; k < 3; ++k)
            det << labels[k] << "\n"
                << "Single time (rank 0): " << singles[k] << "us\n"
                << "Avg single time: " << avgs[k] << "us\n"
                << "Summed time: " << sums[k] << "us\n";
        char tp[64];
        std::snprintf(tp, sizeof(tp), "%.0f",
                      nosetup > 0 ? (double)rows * cols / (nosetup / 1e6) : 0.0);
        det << "Throughput: " << tp << " cells/sec/iter-unit\n"
            << "___________________________________________________\n\n";
    }

    long pop = 0;
    for (uint8_t v : grid) pop += v;
    std::printf("gol_native %s: %lldx%lld x%lld steps, %d workers, "
                "%.3f Gcells/s, population %ld\n",
                name.c_str(), (long long)rows, (long long)cols,
                (long long)iters, p,
                nosetup > 0 ? (double)rows * cols * iters / nosetup / 1e3 : 0.0,
                pop);
    return 0;
}
