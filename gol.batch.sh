#!/usr/bin/env bash
# Production batch launch — the role of the reference's PBS job
# (/root/reference/gol.pbs: 5 nodes x 24 ppn, mpirun -np 100
# ./gol 25000 25000 250 1000).
#
# On a TPU pod slice the process model inverts: one Python process per
# host, all chips of the slice joined into one jax.sharding.Mesh; there is
# no mpirun — the TPU runtime supplies the process group and
# jax.distributed.initialize() (no-args) picks it up from the environment.
# Launch this script on every host of the slice (e.g. with
#   gcloud compute tpus tpu-vm ssh $TPU --worker=all --command="...gol.batch.sh"
# ); each host drives its local chips and writes its own shard tiles.
#
# The configuration mirrors the reference's production run scaled to the
# north-star config: 65536^2 grid, 1000 iterations, snapshot every 250.
set -euo pipefail
cd "$(dirname "$0")"

GRID=${GRID:-65536}
ITERS=${ITERS:-1000}
GAP=${GAP:-250}
SEED=${SEED:-1}
# Snapshots default ON (SAVE=0 disables): without --save the run would
# produce no grid output at all on a multihost slice, where run_tpu
# returns no final grid to the driver process.  At this config the
# snapshot-format auto threshold picks packed .golp tiles (1 bit/cell:
# ~537 MB per 65536^2 snapshot instead of ~8.6 GB of .gol text); force
# SNAPSHOT_FORMAT=gol only if reference-era tooling must read the tiles
# directly.
SAVE=${SAVE:-1}

# MULTIHOST=1 joins the slice-wide process group (set it when launching on
# every host of a pod slice; leave unset for single-host runs).  The run
# name must be identical on every host, so derive it from the config
# rather than per-host timestamps.
NAME=${NAME:-batch-${GRID}x${GRID}-${ITERS}-s${SEED}}

SAVE_FLAG=--save
[ "$SAVE" = 0 ] && SAVE_FLAG=

# PYTHON override: test harnesses / venvs pin the exact interpreter
"${PYTHON:-python}" -m mpi_tpu.cli "$GRID" "$GRID" "$GAP" "$ITERS" batch_timings "${FIRST:-1}" \
  --backend tpu --seed "$SEED" --name "$NAME" $SAVE_FLAG \
  --snapshot-format "${SNAPSHOT_FORMAT:-auto}" \
  ${MULTIHOST:+--multihost} --out-dir "${OUT_DIR:-.}"
